"""PS client session: the worker side of PS-parity mode.

The reference worker talks to servers through ps-lite ZPush/ZPull with
per-partition keys spread over servers by hash
(reference: core_loops.cc:536-616, global.cc:643-692).  This is the
TPU-host redesign of that data path:

  - every tensor is split into <= BYTEPS_PARTITION_BYTES partitions with
    per-partition keys `declared_key << 16 | part_idx`
    (reference: operations.cc:140-180, 301-311),
  - each partition key is placed on a server by the configured hash with
    accumulated-load logging (reference: global.cc:643-692),
  - partition pushes are issued by a dispatcher thread in
    (priority desc, key asc) order through the native priority
    ScheduledQueue, gated by a credit of
    BYTEPS_SCHEDULING_CREDIT x BYTEPS_PARTITION_BYTES bytes in flight;
    completions return credit (reference: scheduled_queue.cc:26-46,136-139),
  - each connection multiplexes outstanding requests by req_id, the
    redesign of ps-lite's completion callbacks (core_loops.cc:536-616),
    so per-partition pushes/pulls to one server pipeline instead of
    serializing on a blocking round-trip,
  - codec work rides a CompressionPool (BYTEPS_TPU_COMPRESS_THREADS,
    the redesign of the reference's COMPRESS/DECOMPRESS pipeline loop
    threads, core_loops.cc): partitions are encoded ahead of the
    dispatcher in the same (priority desc, key asc) order, so the wire
    send of partition k overlaps the encode of k+1, and compressed pull
    payloads are decoded off the receiver thread, so one slow decode
    never stalls other partitions' responses on the same socket.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..common.config import Config
from ..common.logging import get_logger
from ..core.native import get_core
from .codec_pool import CompressionPool

_REQ = struct.Struct("<BBHIIQQ")   # cmd dtype flags req_id worker_id key len
_RESP = struct.Struct("<BIQQ")     # status req_id key len

CMD_HELLO, CMD_INIT, CMD_PUSH, CMD_PULL, CMD_BARRIER, CMD_SHUTDOWN, \
    CMD_PING, CMD_LR_SCALE = range(8)

# dtype byte on the wire (server.cc WireDtype)
DT_F32, DT_RAW, DT_COMPRESSED, DT_SEED = 0, 1, 2, 3


class _Future:
    """Completion slot for one outstanding request."""

    __slots__ = ("event", "data", "error", "callback", "sink")

    def __init__(self, callback: Optional[Callable] = None,
                 sink: Optional[memoryview] = None):
        self.event = None if callback else threading.Event()
        self.data: bytes = b""
        self.error: Optional[Exception] = None
        self.callback = callback
        # Optional preallocated destination: a response whose payload length
        # matches len(sink) is received straight into it (no intermediate
        # buffer — the ZPull-into-shm stance, reference core_loops.cc:582-616).
        self.sink = sink

    def resolve(self, data: bytes, error: Optional[Exception]) -> None:
        self.data, self.error = data, error
        if self.callback is not None:
            self.callback(data, error)
        else:
            self.event.set()

    def wait(self, timeout: Optional[float] = None) -> bytes:
        if not self.event.wait(timeout):
            raise TimeoutError("PS request timed out")
        if self.error is not None:
            raise self.error
        return self.data


class _ServerConn:
    """One multiplexed connection to a PS server.

    Any thread may `send`; a dedicated receiver thread matches responses to
    futures by req_id and runs completion callbacks (the ZPush/ZPull
    callback model, reference: core_loops.cc:564-616).
    """

    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.settimeout(None)  # receiver blocks until data or close
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.lock = threading.Lock()          # send serialization
        self._pending: Dict[int, _Future] = {}
        self._pending_lock = threading.Lock()
        self._req_counter = 0
        self._closed = False
        self._recv_thread = threading.Thread(
            target=self._recv_loop, daemon=True, name="bps-ps-recv")
        self._recv_thread.start()

    def send(self, cmd: int, key: int = 0, payload: bytes = b"",
             worker_id: int = 0, dtype: int = 0, flags: int = 0,
             callback: Optional[Callable] = None,
             sink: Optional[memoryview] = None) -> _Future:
        fut = _Future(callback, sink)
        with self._pending_lock:
            if self._closed:
                raise ConnectionError("PS connection closed")
            self._req_counter = (self._req_counter + 1) & 0xFFFFFFFF
            req_id = self._req_counter
            self._pending[req_id] = fut
        hdr = _REQ.pack(cmd, dtype, flags & 0xFFFF, req_id, worker_id, key,
                        len(payload))
        try:
            with self.lock:
                if len(payload) >= 65536:
                    # Zero-copy gather send for data partitions: the
                    # memoryview goes straight to the socket (the
                    # reference's ZPush zero-copy SArray stance,
                    # core_loops.cc:564-569) and header+payload ride ONE
                    # sendmsg — under TCP_NODELAY a separate header
                    # sendall is its own packet + syscall + server-reader
                    # wakeup per partition (mirror of the server-side
                    # Respond coalescing).
                    self._send_gather(hdr, payload)
                else:
                    self.sock.sendall(hdr + bytes(payload))
        except OSError as e:
            with self._pending_lock:
                self._pending.pop(req_id, None)
            raise ConnectionError(f"PS send failed: {e}") from e
        return fut

    def _send_gather(self, hdr: bytes, payload) -> None:
        """header+payload in one gather syscall, with the partial-write
        loop sendmsg needs (unlike sendall it returns after one write)."""
        mv_h, mv_p = memoryview(hdr), memoryview(payload)
        total = len(mv_h) + len(mv_p)
        sent = self.sock.sendmsg([mv_h, mv_p])
        while sent < total:
            if sent < len(mv_h):
                sent += self.sock.sendmsg([mv_h[sent:], mv_p])
            else:
                self.sock.sendall(mv_p[sent - len(mv_h):])
                sent = total

    def request(self, cmd: int, key: int = 0, payload: bytes = b"",
                worker_id: int = 0, dtype: int = 0, flags: int = 0,
                timeout: Optional[float] = 60.0) -> bytes:
        """Blocking request/response (INIT, BARRIER, control commands).

        BARRIER legitimately blocks on peers, so it is sent without a
        deadline; everything else fails loudly after `timeout` instead of
        hanging a training job on a wedged server.
        """
        if cmd == CMD_BARRIER:
            timeout = None
        return self.send(cmd, key, payload, worker_id, dtype,
                         flags).wait(timeout)

    def _recv_loop(self) -> None:
        try:
            while True:
                buf = self._recv_exact(_RESP.size)
                status, req_id, rkey, length = _RESP.unpack(buf)
                # Pop BEFORE the payload read: this thread owns the future
                # (and its sink buffer) exclusively, so a concurrent
                # _fail_pending can neither resolve it mid-write nor race a
                # retry into the same sink.  The except arm below resolves
                # it if the connection dies mid-payload — no orphaning.
                with self._pending_lock:
                    fut = self._pending.pop(req_id, None)
                try:
                    if (fut is not None and fut.sink is not None
                            and status == 0 and length == len(fut.sink)):
                        # Matched sink: payload lands in the caller's buffer.
                        self._recv_into(fut.sink)
                        data = fut.sink
                    else:
                        data = self._recv_exact(length) if length else b""
                except (ConnectionError, OSError) as e:
                    if fut is not None:
                        try:
                            fut.resolve(
                                b"", ConnectionError(f"PS connection lost "
                                                     f"mid-payload: {e}"))
                        except Exception:
                            get_logger().exception(
                                "PS completion callback failed")
                    raise
                if fut is None:
                    continue  # response for a cancelled request
                err = (RuntimeError(f"PS server error for key {rkey}")
                       if status != 0 else None)
                try:
                    fut.resolve(data, err)
                except Exception:
                    get_logger().exception("PS completion callback failed")
        except (ConnectionError, OSError) as e:
            self._fail_pending(e)

    def _fail_pending(self, exc: Exception) -> None:
        with self._pending_lock:
            self._closed = True
            pending, self._pending = self._pending, {}
        for fut in pending.values():
            try:
                fut.resolve(b"", ConnectionError(f"PS connection lost: {exc}"))
            except Exception:
                pass

    def _recv_exact(self, n: int):
        # recv_into a single preallocated buffer: no per-chunk allocation
        # and no join copy (a 4MB partition pull is one buffer, filled in
        # place).  Callers treat the result as a read-only byte buffer.
        buf = bytearray(n)
        self._recv_into(memoryview(buf))
        return buf

    def _recv_into(self, view: memoryview) -> None:
        n = len(view)
        got = 0
        while got < n:
            r = self.sock.recv_into(view[got:], n - got)
            if r == 0:
                raise ConnectionError("PS server closed connection")
            got += r

    def close(self):
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        self._fail_pending(ConnectionError("closed"))


class PSHandle:
    """Async push_pull completion handle (the torch-plugin handle analog,
    reference: handle_manager.h:33-46)."""

    def __init__(self, shape, dtype, num_parts: int, out: np.ndarray):
        self.shape = shape
        self.dtype = dtype
        self.out = out                      # flat f32 result buffer
        self._remaining = num_parts
        self._lock = threading.Lock()
        self._event = threading.Event()
        self._error: Optional[Exception] = None

    def _part_done(self, error: Optional[Exception] = None) -> None:
        with self._lock:
            if error is not None and self._error is None:
                self._error = error
            self._remaining -= 1
            done = self._remaining <= 0
        if done or error is not None:
            self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = 300.0) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError("PS push_pull timed out")
        if self._error is not None:
            raise self._error
        return self.out.reshape(self.shape).astype(self.dtype, copy=False)


class _PartTask:
    """One in-flight partition (the reference's TensorTableEntry partition,
    common.h:221-264)."""

    __slots__ = ("pkey", "payload", "off", "ln", "round", "conn", "handle",
                 "dtype", "done_evt", "wire_ln", "bidirectional",
                 "label", "priority", "enq_ts", "push_ts", "pull_ts",
                 "ready", "enc_err", "credit_ln")

    def __init__(self, pkey, payload, off, ln, rnd, conn, handle,
                 dtype=DT_F32, bidirectional=False, label=""):
        self.pkey = pkey
        self.payload = payload        # wire bytes (raw f32 or compressed);
        #                               None while a pipelined encode runs
        self.off = off                # raw byte offset in the tensor
        self.ln = ln                  # raw byte length of the partition
        self.wire_ln = len(payload) if payload is not None else ln
        self.round = rnd
        self.conn = conn
        self.handle = handle
        self.dtype = dtype
        self.bidirectional = bidirectional  # pull leg may arrive compressed
        self.done_evt = threading.Event()  # this partition left _inflight
        # Per-partition trace spans (reference closes one span per partition
        # per stage, global.cc:463-579): QUEUE = enq->dispatch,
        # PUSH = dispatch->ack, PULL = issue->data.
        self.label = label
        self.priority = 0
        self.enq_ts = 0
        self.push_ts = 0
        self.pull_ts = 0
        # Codec pipeline state: `ready` is set once the pool has produced
        # (or failed to produce) this partition's wire payload; None means
        # the payload was ready at staging time (raw parts, inline mode).
        self.ready = None
        self.enc_err = None
        # Scheduling-credit charge: actual wire bytes when known, else
        # the codec's worst-case bound (set by _stage_parts for pipelined
        # encodes, whose true size doesn't exist at enqueue time).
        self.credit_ln = self.wire_ln


class PSSession:
    """One worker's sessions to all PS servers.

    push_pull partitions the tensor, spreads partitions across servers, and
    drives them through the priority-scheduled, credit-gated dispatcher —
    the eager analog of the reference's PUSH/PULL loops
    (reference: core_loops.cc:536-616, operations.cc:429-485).
    """

    def __init__(self, hosts: List[str], ports: List[int], worker_id: int,
                 num_servers: int, hash_fn: str = "djb2",
                 partition_bytes: int = 4 * 1024 * 1024,
                 scheduling_credit: int = 0,
                 min_compress_bytes: int = 65536,
                 wire_conns: int = 2,
                 compress_threads: int = 2):
        self.worker_id = worker_id
        self.num_servers = max(1, num_servers)
        self.hash_fn = hash_fn
        self.partition_bytes = max(1, partition_bytes)
        # Partitions below this size skip compression — the
        # BYTEPS_MIN_COMPRESS_BYTES floor (reference: global.cc:43,
        # operations.cc:362-364).
        self.min_compress_bytes = min_compress_bytes
        # Codec pipeline width (BYTEPS_TPU_COMPRESS_THREADS).  0 = inline
        # fallback: encode on the caller thread, decode on the receiver
        # thread, exactly the pre-pipeline data path.
        self.compress_threads = max(0, compress_threads)
        # Any failure before __init__ returns (a connect, the dispatcher,
        # the HELLO mode check) must tear down every socket and receiver
        # thread already created — the caller gets an exception, not a
        # session, so nothing else can ever close them.
        self.conns: List[_ServerConn] = []
        self._data_conns: List[List[_ServerConn]] = []
        try:
            self._init_connections(hosts, ports, max(1, wire_conns))
            self._init_state(scheduling_credit)
            self._hello_mode_check(worker_id)
        except Exception:
            self._abort_init()
            raise

    def _init_connections(self, hosts, ports, wire_conns: int) -> None:
        """Primary conn per server + optional extra data connections.

        Partitions stripe across a server's pool, splitting the send-lock
        and receive-thread work over more sockets (the reference gets the
        same effect from ps-lite's per-connection threads).  Control
        traffic (barrier/hello/shutdown) stays on the primary."""
        for h, p in zip(hosts, ports):
            c = _ServerConn(h, p)
            self.conns.append(c)
            self._data_conns.append([c])
        for pool, (h, p) in zip(self._data_conns, zip(hosts, ports)):
            for _ in range(wire_conns - 1):
                pool.append(_ServerConn(h, p))
        # Per-server round-robin cursor, persistent across plans: a
        # per-plan counter would pin every single-partition tensor (the
        # common case for DL gradients) to the primary socket.
        self._conn_rr = [0] * len(self.conns)

    def _abort_init(self) -> None:
        if getattr(self, "_dispatcher", None) is not None:
            with self._cv:
                self._closed = True
                self._cv.notify_all()
            self._dispatcher.join(timeout=5)
        if getattr(self, "_codec_pool", None) is not None:
            self._codec_pool.close()
        for pool in self._data_conns:
            for c in pool:
                c.close()

    def _init_state(self, scheduling_credit: int) -> None:
        self._inited: Dict[int, tuple] = {}     # pkey -> (length, kwargs)
        self._round: Dict[int, int] = {}        # pkey -> next round index
        self._compressors: Dict[int, object] = {}  # declared_key -> codec
        self._server_load = [0] * len(self.conns)
        self._plans: Dict[Tuple[int, int], list] = {}
        # _plan's read-modify-write of _plans/_conn_rr/_server_load must be
        # atomic: two threads planning concurrently would double-count
        # server load and cache divergent stripe assignments.
        self._plan_lock = threading.Lock()
        self._trace_labels: Dict[int, str] = {}

        # Dispatcher: native priority ScheduledQueue + credit flow control
        # (reference: scheduled_queue.cc:26-46,136-139).  credit = 0 means
        # unlimited in-flight bytes, matching the reference default.
        credit_bytes = scheduling_credit * self.partition_bytes
        if credit_bytes > 0:
            credit_bytes = max(credit_bytes, self.partition_bytes)
        self._queue = get_core().queue_create(credit_bytes)
        # Codec pipeline engine (the reference's COMPRESS/DECOMPRESS loop
        # threads, core_loops.cc): encodes run ahead of the dispatcher in
        # the same (priority desc, key asc) order, decodes run off the
        # receiver thread.  NOTE: with the pipeline on, a compressed
        # partition's credit is charged at the codec's worst-case wire
        # size (WireCompressor.wire_cap_bytes, clamped to raw size) —
        # the true encoded size is not known at enqueue time.
        self._codec_pool = (CompressionPool(self.compress_threads)
                            if self.compress_threads > 0 else None)
        self._inflight: Dict[int, _PartTask] = {}
        self._inflight_lock = threading.Lock()
        self._cv = threading.Condition()
        self._closed = False
        self._paused = False
        # Dispatch-order recording is off by default: the list is unbounded
        # and only priority-order tests/tracing read it.
        self.record_push_order = False
        self.push_order: List[int] = []
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True, name="bps-ps-dispatch")
        self._dispatcher.start()

    def _hello_mode_check(self, worker_id: int) -> None:
        # HELLO returns the server's mode flags (u8 async | u8 schedule).
        # All servers must agree — a mixed fleet silently corrupts training
        # (partitions on a sync server would round-SUM async deltas).
        modes = []
        for c in self.conns:
            mode = c.request(CMD_HELLO, worker_id=worker_id)
            modes.append((bool(mode[0]), bool(mode[1]))
                         if len(mode) >= 2 else (False, False))
        if len(set(modes)) > 1:
            raise RuntimeError(
                f"PS servers report mixed modes (async, schedule): {modes}; "
                "all servers must share BYTEPS_ENABLE_ASYNC / "
                "BYTEPS_SERVER_ENABLE_SCHEDULE settings")
        self.server_async, self.server_schedule = modes[0]

    @classmethod
    def from_config(cls, cfg: Config) -> "PSSession":
        n = max(1, cfg.num_server)
        # Single-host convention: servers at scheduler_port+1+i.  Multi-host
        # deployments list hosts via BYTEPS_TPU_PS_HOSTS=host:port,host:port.
        import os
        spec = os.environ.get("BYTEPS_TPU_PS_HOSTS", "")
        if spec:
            pairs = [s.rsplit(":", 1) for s in spec.split(",") if s]
            hosts = [p[0] for p in pairs]
            ports = [int(p[1]) for p in pairs]
        else:
            hosts = [cfg.scheduler_uri] * n
            ports = [cfg.scheduler_port + 1 + i for i in range(n)]
        return cls(hosts, ports, cfg.worker_id, n, cfg.key_hash_fn,
                   partition_bytes=cfg.partition_bytes,
                   scheduling_credit=cfg.scheduling_credit,
                   min_compress_bytes=cfg.min_compress_bytes,
                   wire_conns=cfg.wire_conns,
                   compress_threads=cfg.compress_threads)

    def set_lr_scale(self, scale: float) -> None:
        """One-shot EF-error rescale after a learning-rate change;
        `scale` = prev_lr / new_lr (reference `lr.s` mechanism; see
        WireCompressor.set_lr_scale).

        Covers BOTH EF legs: the local worker-side errors, and — from
        worker 0 only, so N workers don't compound the rescale N times —
        the servers' recompress-leg errors via CMD_LR_SCALE.  Call between
        steps on every worker (each owns its local errors).
        """
        for comp in self._compressors.values():
            comp.set_lr_scale(scale)
        if self.worker_id == 0:
            payload = struct.pack("<f", float(scale))
            for c in self.conns:
                c.request(CMD_LR_SCALE, 0, payload,
                          worker_id=self.worker_id)

    def register_compressor(self, declared_key: int, kwargs: dict) -> None:
        """Register an inter-node compressor for a tensor's PS traffic.

        Must be called before the tensor's first push_pull: the kwargs are
        shipped to the server in each partition's INIT (the
        kCompressedPushPull analog, reference: operations.cc:396-408,
        server.cc:232-261), and the server builds its decompress-sum(-
        recompress) path from them.
        """
        from .wire import WireCompressor
        self._compressors[declared_key] = WireCompressor(
            {str(k): str(v) for k, v in kwargs.items()})

    # -- partition planning -------------------------------------------------
    def _plan(self, declared_key: int, nbytes: int) -> list:
        """[(pkey, offset, length, conn)] for a tensor of `nbytes` bytes.

        Partition bounds and key encoding come from the native core; server
        placement uses the configured hash over the partition key, with
        accumulated per-server load logged like the reference's placement
        summary (reference: global.cc:643-692, 675-682).
        """
        with self._plan_lock:
            cached = self._plans.get((declared_key, nbytes))
            if cached is not None:
                return cached
            core = get_core()
            bounds = core.partition_bounds(nbytes, self.partition_bytes)
            plan = []
            # Stripe by a per-server cursor that persists across plans (in
            # self._conn_rr): a global-index stripe degenerates when
            # placement correlates with index (hash_fn=naive), and a
            # per-plan counter pins every single-partition tensor to the
            # primary socket.  Plans are cached, so each partition's conn
            # assignment is stable.
            for idx, (off, ln) in enumerate(bounds):
                pkey = core.encode_key(declared_key, idx)
                srv = core.key_to_server(pkey, len(self.conns), self.hash_fn)
                self._server_load[srv] += ln
                pool = self._data_conns[srv]
                plan.append((pkey, off, ln,
                             pool[self._conn_rr[srv] % len(pool)]))
                self._conn_rr[srv] += 1
            self._plans[(declared_key, nbytes)] = plan
            total = sum(self._server_load) or 1
        get_logger().debug(
            "PS placement: tensor key=%d parts=%d; server load %s",
            declared_key, len(plan),
            ["%.1f%%" % (100.0 * l / total) for l in self._server_load])
        return plan

    # -- dispatcher ---------------------------------------------------------
    def _dispatch_loop(self) -> None:
        while True:
            with self._cv:
                while not self._closed and (
                        self._paused or self._queue.pending() == 0):
                    self._cv.wait()
                if self._closed:
                    return
                task = self._queue.get()
                if task is None:
                    # Credit exhausted: wait for report_finish to return it.
                    self._cv.wait(timeout=1.0)
                    continue
            pkey, _prio, nbytes = task
            with self._inflight_lock:
                part = self._inflight.get(pkey)
            if part is None:  # cancelled (session closing)
                self._queue.report_finish(nbytes)
                continue
            if self.record_push_order:
                self.push_order.append(pkey)
            if part.ready is not None and not part.ready.is_set():
                # Codec pipeline: the pool encodes in this same
                # (priority desc, key asc) order ahead of this loop, so
                # the wait is the pipeline-fill case (first partition) or
                # an encoder still catching up — either way the pool keeps
                # working k+1 while k's bytes go out below.
                while not part.ready.wait(timeout=1.0):
                    with self._cv:
                        if self._closed:
                            self._queue.report_finish(nbytes)
                            return
            if part.enc_err is not None:
                self._queue.report_finish(nbytes)
                with self._cv:
                    self._cv.notify_all()
                self._finish_part(pkey, part.enc_err)
                continue
            core = get_core()
            if core.trace_on and part.enq_ts:
                part.push_ts = core.trace_now_us()
                core.trace_record_part(part.label, "QUEUE", part.enq_ts,
                                       part.push_ts - part.enq_ts, pkey,
                                       part.wire_ln, part.priority)
            try:
                part.conn.send(
                    CMD_PUSH, pkey, part.payload, worker_id=self.worker_id,
                    dtype=part.dtype, flags=part.round,
                    callback=lambda data, err, pkey=pkey, nbytes=nbytes:
                        self._on_push_ack(pkey, nbytes, err))
            except ConnectionError as e:
                self._queue.report_finish(nbytes)
                self._finish_part(pkey, e)

    def _on_push_ack(self, pkey: int, nbytes: int,
                     error: Optional[Exception]) -> None:
        # Push landed on the server: return its credit (the reference
        # reportFinish, scheduled_queue.cc:197-203) and issue the pull.
        self._queue.report_finish(nbytes)
        with self._cv:
            self._cv.notify_all()
        if error is not None:
            self._finish_part(pkey, error)
            return
        with self._inflight_lock:
            part = self._inflight.get(pkey)
        if part is None:
            return
        core = get_core()
        if core.trace_on and part.push_ts:
            part.pull_ts = core.trace_now_us()
            core.trace_record_part(part.label, "PUSH", part.push_ts,
                                   part.pull_ts - part.push_ts, pkey,
                                   part.wire_ln, part.priority)
        try:
            # Non-compressed pulls land straight in the output buffer (the
            # receiver matches on length); bidirectional compressed pulls
            # come back re-encoded at a different length and take the
            # allocating path + wire_decode.
            sink = None
            if not part.bidirectional:
                sink = memoryview(part.handle.out).cast("B")[
                    part.off:part.off + part.ln]
            part.conn.send(
                CMD_PULL, pkey, worker_id=self.worker_id, flags=part.round,
                sink=sink,
                callback=lambda data, err, pkey=pkey:
                    self._on_pull(pkey, data, err))
        except ConnectionError as e:
            self._finish_part(pkey, e)

    def _on_pull(self, pkey: int, data: bytes,
                 error: Optional[Exception]) -> None:
        if error is not None:
            self._finish_part(pkey, error)
            return
        with self._inflight_lock:
            part = self._inflight.pop(pkey, None)
            if part is not None:
                # Bump inside the lock: a waiter in push_pull_async must see
                # the new round the moment the key leaves _inflight.
                self._round[pkey] = part.round + 1
        if part is None:
            return
        core = get_core()
        if core.trace_on and part.pull_ts:
            core.trace_record_part(part.label, "PULL", part.pull_ts,
                                   core.trace_now_us() - part.pull_ts, pkey,
                                   len(data), part.priority)
        if (self._codec_pool is not None and part.bidirectional
                and not isinstance(data, memoryview)
                and len(data) != part.ln):
            # Compressed pull payload: decode OFF the receiver thread, so
            # one slow decode cannot stall every other partition's
            # response parsing on this socket (the reference's DECOMPRESS
            # loop thread, core_loops.cc:618-646).  The part already left
            # _inflight above, so a staged re-push of the same key
            # proceeds while this round's payload decodes.
            try:
                self._codec_pool.submit(
                    part.priority, pkey,
                    lambda part=part, data=data:
                        self._complete_pull(part, data))
                return
            except RuntimeError:
                pass    # pool already closing: finish inline below
        self._complete_pull(part, data)

    def _complete_pull(self, part: "_PartTask", data) -> None:
        """Land one pull payload in the handle's output buffer.

        Runs on the receiver thread for raw/sink payloads (a straight
        frombuffer/no-op), and on a codec pool thread for compressed
        payloads (wire_decode is real work) — inline mode
        (compress_threads=0) keeps everything on the receiver thread.
        """
        core = get_core()
        try:
            n = part.ln // 4
            if isinstance(data, memoryview):
                # Sink path: the receiver already landed the payload in
                # part.handle.out (length-matched) — nothing to copy.
                pass
            else:
                if part.bidirectional and len(data) != part.ln:
                    # Bidirectional compressor: the merged buffer came back
                    # re-compressed; decode it (reference: worker DECOMPRESS
                    # stage, core_loops.cc:618-646).
                    from .wire import decode as wire_decode
                    t0 = (core.trace_now_us()
                          if core.trace_on or self._codec_pool is not None
                          else 0)
                    got = wire_decode(bytes(data), n)
                    if t0:
                        dur = core.trace_now_us() - t0
                        if core.trace_on:
                            core.trace_record_part(
                                part.label, "DECODE", t0, dur, part.pkey,
                                len(data), part.priority)
                        if self._codec_pool is not None:
                            self._codec_pool.record("DECODE", dur)
                else:
                    got = np.frombuffer(data, np.float32)
                if got.size != n:
                    raise ValueError(
                        f"PS pull size mismatch for key {part.pkey}: "
                        f"got {got.size} f32, want {n}")
                part.handle.out[part.off // 4:part.off // 4 + n] = got
            part.handle._part_done()
        except Exception as e:
            part.handle._part_done(e)
        finally:
            part.done_evt.set()

    def _finish_part(self, pkey: int, error: Exception) -> None:
        with self._inflight_lock:
            part = self._inflight.pop(pkey, None)
        if part is not None:
            part.handle._part_done(error)
            part.done_evt.set()

    # -- test/introspection hooks -------------------------------------------
    def pause_dispatch(self) -> None:
        """Hold dispatch so several push_pull_async calls can enqueue before
        any push is issued (deterministic priority-order tests)."""
        with self._cv:
            self._paused = True

    def resume_dispatch(self) -> None:
        with self._cv:
            self._paused = False
            self._cv.notify_all()

    # -- public API ---------------------------------------------------------
    def push_pull_async(self, declared_key: int, tensor,
                        priority: int = 0, raw: bool = False,
                        seed: bool = False, copy: bool = False) -> PSHandle:
        """Partitioned, priority-scheduled asynchronous push_pull.

        ZERO-COPY CONTRACT: when `tensor` is already a contiguous float32
        buffer, partitions are wire views of the caller's memory (the
        reference's ZPush zero-copy SArray semantics) — the caller must
        not mutate it until the returned handle completes.  Non-f32 or
        non-contiguous inputs are converted (snapshotted) first.
        copy=True restores the old snapshot semantics unconditionally for
        callers that need to keep mutating the buffer after dispatch
        (documented in docs/migration.md "wire semantics").

        raw=True pushes last-write-wins bytes instead of f32-summed values.
        seed=True (async servers only) writes the store ONLY if the key has
        never been pushed — idempotent initial-weight seeding that cannot
        reset a live run when a worker joins late or rejoins.
        """
        handle, parts = self._stage(declared_key, tensor, priority, raw,
                                    seed, copy)
        self._enqueue([(parts, priority)])
        return handle

    def push_pull_group(self, items, raw: bool = False, seed: bool = False,
                        copy: bool = False) -> List[PSHandle]:
        """Grouped staging: stage EVERY (declared_key, tensor, priority)
        item, then enqueue them all under one dispatcher wakeup.

        This is the fusion layer's dispatch face (common/fusion.py): the
        priority ScheduledQueue sees the whole bucket set before the
        dispatcher picks, so buckets leave in strict (priority desc, key
        asc) order even without a credit limit slowing the first pick —
        and N buckets cost one lock round-trip instead of N.  Each item
        follows the same zero-copy contract as push_pull_async.
        """
        staged: List[tuple] = []
        handles: List[PSHandle] = []
        seen: set = set()
        try:
            for declared_key, tensor, priority in items:
                if declared_key in seen:
                    # A repeated key inside one group would deadlock: its
                    # _stage blocks on the earlier round's completion,
                    # which can't happen until that round is enqueued.
                    # Flush what's staged so the guard can make progress.
                    self._enqueue(staged)
                    staged, seen = [], set()
                h, parts = self._stage(declared_key, tensor, priority, raw,
                                       seed, copy)
                handles.append(h)
                staged.append((parts, priority))
                seen.add(declared_key)
        except Exception:
            # The failing item rolled back its own parts in _stage; the
            # EARLIER items are staged but will never be enqueued — unpin
            # them too, or their keys wedge every later push (the
            # sequential-use guard would wait on done_evts nothing sets).
            with self._inflight_lock:
                for parts, _ in staged:
                    for p in parts:
                        if self._inflight.get(p.pkey) is p:
                            del self._inflight[p.pkey]
                        p.done_evt.set()
            raise
        self._enqueue(staged)
        return handles

    def _stage(self, declared_key: int, tensor, priority: int, raw: bool,
               seed: bool, copy: bool) -> tuple:
        """Partition + stage one tensor into _inflight (INITs included)
        WITHOUT enqueueing — the caller batches the queue adds so grouped
        pushes enter the scheduler atomically."""
        arr = np.asarray(tensor)
        payload = np.ascontiguousarray(arr, dtype=np.float32).ravel()
        if copy and np.may_share_memory(payload, arr):
            # Snapshot only when the wire view would alias the caller's
            # memory — the non-f32/non-contiguous path already copied.
            payload = payload.copy()
        # Zero-copy wire: partitions are sent as memoryview slices of the
        # caller's buffer (no tobytes snapshot) — the reference's ZPush
        # contract: the tensor must not be mutated until the handle
        # completes.  The sequential-use guard in _stage_parts already
        # serializes re-pushes of the same key.
        plan = self._plan(declared_key, payload.nbytes)
        handle = PSHandle(arr.shape, arr.dtype, len(plan),
                          np.zeros(payload.nbytes // 4, np.float32))
        mv = memoryview(payload).cast("B")
        comp = self._compressors.get(declared_key)
        kw_bytes = comp.kwargs_string().encode() if comp else b""
        label = self._label(declared_key)
        parts = []
        try:
            self._stage_parts(plan, payload, mv, comp, kw_bytes, handle,
                              parts, raw, seed, label, priority)
        except Exception:
            # Roll back partitions already staged in _inflight: leaving them
            # would wedge the key forever (the sequential-use guard waits on
            # done_evt, which nothing would ever set).
            with self._inflight_lock:
                for p in parts:
                    if self._inflight.get(p.pkey) is p:
                        del self._inflight[p.pkey]
                    p.done_evt.set()
            raise
        return handle, parts

    def _enqueue(self, staged) -> None:
        """Enqueue staged partitions ([(parts, priority), ...]) into the
        scheduler under ONE condition-variable hold."""
        core = get_core()
        enq = core.trace_now_us() if core.trace_on else 0
        with self._cv:
            for parts, priority in staged:
                for p in parts:
                    p.enq_ts = enq
                    # credit_ln: actual wire bytes for ready parts; the
                    # codec's worst-case bound for pipelined encodes (their
                    # true size doesn't exist yet and p.wire_ln is racing
                    # the encoder).  The queue returns the same figure at
                    # get(), so report_finish stays symmetric either way.
                    self._queue.add(p.pkey, priority, p.credit_ln)
            self._cv.notify_all()

    def _label(self, declared_key: int) -> str:
        """Tensor name for trace rows (falls back to the numeric key for
        sessions driven outside the declare() registry)."""
        lbl = self._trace_labels.get(declared_key)
        if lbl is None:
            name = get_core().declared_name(declared_key)
            lbl = name if name else f"key_{declared_key}"
            self._trace_labels[declared_key] = lbl
        return lbl

    def _init_parts(self, plan, kw_bytes) -> None:
        """Pipelined per-partition CMD_INIT: issue every needed INIT
        concurrently, then await them all — one round-trip time per tensor
        instead of one blocking round-trip per partition (a 64-partition
        tensor's first push used to pay 64 serial RTTs here).  All futures
        resolve before any partition is staged, so the PUSH of a key can
        never beat its INIT to the server."""
        inits = []
        for pkey, off, ln, conn in plan:
            if self._inited.get(pkey) != (ln, kw_bytes):
                init_payload = struct.pack(
                    "<QI", ln, len(kw_bytes)) + kw_bytes
                inits.append((pkey, ln,
                              conn.send(CMD_INIT, pkey, init_payload,
                                        worker_id=self.worker_id)))
        for pkey, ln, fut in inits:
            resp = fut.wait(60.0)
            # Seed the round counter from server state so a reconnected
            # worker can never pull a stale previous round.
            (completed,) = struct.unpack("<Q", resp)
            self._round[pkey] = completed
            self._inited[pkey] = (ln, kw_bytes)

    def _encode_part(self, part: "_PartTask", comp, seg) -> None:
        """Produce one partition's compressed wire payload on a codec pool
        thread, recording the ENCODE span; always resolves part.ready (an
        unset event would hang the dispatcher on this key forever)."""
        core = get_core()
        t0 = core.trace_now_us()
        try:
            blob = comp.encode(part.pkey, seg)
            part.payload = blob
            part.wire_ln = len(blob)
        except Exception as e:
            part.enc_err = e
        finally:
            # ready FIRST: if the tracer/stats below ever raised, an unset
            # event would wedge the in-order dispatcher forever (the
            # pool's catch-all only logs).
            part.ready.set()
            dur = core.trace_now_us() - t0
            if core.trace_on:
                core.trace_record_part(part.label, "ENCODE", t0, dur,
                                       part.pkey, part.wire_ln,
                                       part.priority)
            self._codec_pool.record("ENCODE", dur)

    def _stage_parts(self, plan, payload, mv, comp, kw_bytes, handle,
                     parts, raw, seed, label="", priority=0) -> None:
        self._init_parts(plan, kw_bytes)
        pool = self._codec_pool
        core = get_core()
        for pkey, off, ln, conn in plan:
            # BYTEPS_MIN_COMPRESS_BYTES floor: small partitions go raw
            # (reference: operations.cc:362-364).
            use_comp = (comp is not None and not raw and not seed
                        and ln >= self.min_compress_bytes)
            if use_comp and pool is None:
                # Inline fallback (BYTEPS_TPU_COMPRESS_THREADS=0): encode
                # on the caller thread, the pre-pipeline data path.
                t0 = core.trace_now_us() if core.trace_on else 0
                wire_payload = comp.encode(
                    pkey, payload[off // 4:(off + ln) // 4])
                if t0:
                    core.trace_record_part(
                        f"{label}.part{pkey & 0xFFFF}", "ENCODE", t0,
                        core.trace_now_us() - t0, pkey, len(wire_payload),
                        priority)
                dtype = DT_COMPRESSED
            elif use_comp:
                wire_payload = None     # pipelined: the pool fills it in
                dtype = DT_COMPRESSED
            else:
                wire_payload = mv[off:off + ln]
                dtype = DT_SEED if seed else (DT_RAW if raw else DT_F32)
            # Sequential-use guard: a second async push_pull of the same
            # tensor before the first completed waits for that partition.
            # Check-and-insert is atomic under _inflight_lock, and the round
            # tag is read inside the same critical section (after any
            # previous round's _on_pull bumped it).
            while True:
                with self._inflight_lock:
                    prev = self._inflight.get(pkey)
                    if prev is None:
                        part = _PartTask(
                            pkey, wire_payload, off, ln,
                            self._round.get(pkey, 0), conn, handle,
                            dtype=dtype,
                            bidirectional=use_comp and comp.bidirectional,
                            label=f"{label}.part{pkey & 0xFFFF}")
                        part.priority = priority
                        if wire_payload is None:
                            part.ready = threading.Event()
                            # Credit charge for a not-yet-encoded part:
                            # the codec's worst-case wire size (never the
                            # raw 4n — that would cut credit-gated
                            # concurrency by the compression ratio).
                            part.credit_ln = min(
                                ln, comp.wire_cap_bytes(ln // 4))
                        self._inflight[pkey] = part
                        parts.append(part)
                        break
                prev.done_evt.wait(timeout=60.0)
            if part.ready is not None:
                # Submitted AFTER the guard admits the part, so the encoder
                # reads this round's EF/momentum/PRNG state strictly after
                # the previous round's encode finished with it; the pool
                # drains jobs in (priority desc, key asc) order, ahead of
                # the dispatcher's identical order, overlapping partition
                # k's wire send with the encode of k+1.
                seg = payload[off // 4:(off + ln) // 4]
                pool.submit(priority, pkey,
                            lambda part=part, seg=seg:
                                self._encode_part(part, comp, seg))

    def push_pull(self, key: int, tensor, priority: int = 0,
                  **kw) -> np.ndarray:
        return self.push_pull_async(key, tensor, priority, **kw).wait()

    def barrier(self, generation: int = 0) -> None:
        """Global barrier across workers (reference: Postoffice::Barrier via
        the scheduler; here server 0 plays the rendezvous role)."""
        self.conns[0].request(CMD_BARRIER, generation,
                              worker_id=self.worker_id)

    def shutdown_servers(self) -> None:
        for c in self.conns:
            try:
                c.request(CMD_SHUTDOWN, worker_id=self.worker_id)
            except (ConnectionError, OSError) as e:
                get_logger().debug("shutdown race: %s", e)

    def codec_stats(self) -> dict:
        """Codec pipeline counters (parts encoded/decoded off-thread and
        busy time); zeros with the pipeline disabled (compress_threads=0,
        where codec work runs inline on the caller/receiver threads)."""
        if self._codec_pool is None:
            return dict(CompressionPool.ZERO_STATS)
        return self._codec_pool.stats()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        # Dispatcher first (it may be waiting on an encode the pool still
        # owes), then the codec pool (drains queued jobs so every staged
        # handle resolves), then the sockets.
        self._dispatcher.join(timeout=10)
        if self._codec_pool is not None:
            self._codec_pool.close()
        for pool in self._data_conns:
            for c in pool:
                c.close()
