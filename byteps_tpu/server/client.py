"""PS client session: the worker side of PS-parity mode.

The reference worker talks to servers through ps-lite ZPush/ZPull with
per-partition keys spread over servers by hash
(reference: core_loops.cc:536-616, global.cc:643-692).  Here each worker
process holds one TCP session per server; tensors are pushed/pulled by
their framework key, with key -> server placement delegated to the native
core's hash functions so the layout matches the reference scheme.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Dict, List, Optional

import numpy as np

from ..common.config import Config
from ..common.logging import get_logger
from ..core.native import get_core

_REQ = struct.Struct("<BBHIQQ")   # cmd dtype flags worker_id key len
_RESP = struct.Struct("<BQQ")     # status key len

CMD_HELLO, CMD_INIT, CMD_PUSH, CMD_PULL, CMD_BARRIER, CMD_SHUTDOWN, \
    CMD_PING = range(7)


class _ServerConn:
    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.lock = threading.Lock()

    def request(self, cmd: int, key: int = 0, payload: bytes = b"",
                worker_id: int = 0, dtype: int = 0, flags: int = 0) -> bytes:
        with self.lock:
            hdr = _REQ.pack(cmd, dtype, flags & 0xFFFF, worker_id, key,
                            len(payload))
            self.sock.sendall(hdr + payload)
            return self._read_response(key)

    def _read_response(self, key: int) -> bytes:
        buf = self._recv_exact(_RESP.size)
        status, rkey, length = _RESP.unpack(buf)
        data = self._recv_exact(length) if length else b""
        if status != 0:
            raise RuntimeError(f"PS server error for key {rkey}")
        return data

    def _recv_exact(self, n: int) -> bytes:
        chunks = []
        while n > 0:
            c = self.sock.recv(n)
            if not c:
                raise ConnectionError("PS server closed connection")
            chunks.append(c)
            n -= len(c)
        return b"".join(chunks)

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


class PSSession:
    """One worker's sessions to all PS servers.

    push_pull(key, array) pushes the f32 payload and pulls the across-worker
    sum — the eager analog of the reference's PUSH→PULL queue pair
    (reference: operations.cc:429-485).  Partitioning happens above this
    layer (api.push_pull hands in whole tensors; partition-level keys use
    the core's encode_key scheme).
    """

    def __init__(self, hosts: List[str], ports: List[int], worker_id: int,
                 num_servers: int, hash_fn: str = "djb2"):
        self.worker_id = worker_id
        self.num_servers = max(1, num_servers)
        self.hash_fn = hash_fn
        self.conns = [_ServerConn(h, p) for h, p in zip(hosts, ports)]
        self._inited: Dict[int, int] = {}
        self._round: Dict[int, int] = {}  # per-key push_pull round counter
        for c in self.conns:
            c.request(CMD_HELLO, worker_id=worker_id)

    @classmethod
    def from_config(cls, cfg: Config) -> "PSSession":
        n = max(1, cfg.num_server)
        # Single-host convention: servers at scheduler_port+1+i.  Multi-host
        # deployments list hosts via BYTEPS_TPU_PS_HOSTS=host:port,host:port.
        import os
        spec = os.environ.get("BYTEPS_TPU_PS_HOSTS", "")
        if spec:
            pairs = [s.rsplit(":", 1) for s in spec.split(",") if s]
            hosts = [p[0] for p in pairs]
            ports = [int(p[1]) for p in pairs]
        else:
            hosts = [cfg.scheduler_uri] * n
            ports = [cfg.scheduler_port + 1 + i for i in range(n)]
        return cls(hosts, ports, cfg.worker_id, n, cfg.key_hash_fn)

    def _conn_for(self, key: int) -> _ServerConn:
        idx = get_core().key_to_server(key, len(self.conns), self.hash_fn)
        return self.conns[idx]

    def push_pull(self, key: int, tensor, priority: int = 0) -> np.ndarray:
        del priority  # ordering is applied by the caller's scheduler
        arr = np.asarray(tensor)
        orig_dtype = arr.dtype
        orig_shape = arr.shape
        payload = np.ascontiguousarray(arr, dtype=np.float32).tobytes()
        conn = self._conn_for(key)
        if self._inited.get(key) != len(payload):
            conn.request(CMD_INIT, key,
                         struct.pack("<Q", len(payload)),
                         worker_id=self.worker_id)
            self._inited[key] = len(payload)
        # The round tag makes a straggler's pull match the round it pushed,
        # even if a fast peer has already started merging the next round
        # (server keeps the last published round in a separate buffer).
        rnd = self._round.get(key, 0)
        conn.request(CMD_PUSH, key, payload, worker_id=self.worker_id,
                     flags=rnd)
        data = conn.request(CMD_PULL, key, worker_id=self.worker_id,
                            flags=rnd)
        self._round[key] = rnd + 1
        out = np.frombuffer(data, np.float32).reshape(orig_shape)
        return out.astype(orig_dtype, copy=False)

    def barrier(self, generation: int = 0) -> None:
        """Global barrier across workers (reference: Postoffice::Barrier via
        the scheduler; here server 0 plays the rendezvous role)."""
        self.conns[0].request(CMD_BARRIER, generation,
                              worker_id=self.worker_id)

    def shutdown_servers(self) -> None:
        for c in self.conns:
            try:
                c.request(CMD_SHUTDOWN, worker_id=self.worker_id)
            except (ConnectionError, OSError) as e:
                get_logger().debug("shutdown race: %s", e)

    def close(self) -> None:
        for c in self.conns:
            c.close()
