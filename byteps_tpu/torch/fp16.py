"""Half-precision distributed optimizer: fp16/bf16 wire, fp32 master weights.

The reference ships this as `_HalfPrecisionDistributedOptimizer`
(reference: byteps/misc/imagenet18/__init__.py:39-330): the model holds
half-precision parameters, gradients travel the wire compressed, an fp32
master copy of every parameter accumulates the updates, and the masters are
cast back into the model after each step.  Loss scaling keeps small
gradients representable in half precision.

TPU-native differences, same contract:
  - the wire cast is the framework's Compression.fp16 (bf16 on TPU — same
    exponent range as fp32, so loss scaling is needed only for true fp16
    models, but the scaler also provides inf/nan skip protection);
  - all per-parameter push_pulls are dispatched asynchronously first and
    synchronized afterwards (JAX async dispatch supplies the overlap the
    reference builds with per-parameter early steps + forward pre-hooks;
    cross-iteration overlap lives in parallel/cross_barrier.py);
  - a dynamic loss scaler (halve on overflow, grow on stability) replaces
    the reference's static `loss_scale` knob, with static still available.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Tuple

import torch

from ..ops.compression import Compression
from . import push_pull_async, synchronize, size


class HalfPrecisionDistributedOptimizer:
    """Distributed optimizer for a half-precision model with fp32 masters.

    Usage::

        model = Net().to(torch.float16)          # or bfloat16
        opt = HalfPrecisionDistributedOptimizer(
            model, lambda params: torch.optim.SGD(params, lr=0.1),
            loss_scale=1024.0)                    # or "dynamic"
        for x, y in data:
            opt.zero_grad()
            loss = criterion(model(x.half()), y)
            opt.scale_loss(loss).backward()
            opt.step()
    """

    def __init__(self, model: torch.nn.Module,
                 optimizer_factory: Callable[[List[torch.Tensor]],
                                             torch.optim.Optimizer],
                 compression=Compression.fp16,
                 loss_scale: object = "dynamic",
                 scale_growth_interval: int = 200,
                 named_parameters: Optional[Iterable[Tuple[str,
                                                           torch.Tensor]]]
                 = None):
        self._model = model
        self._compression = compression
        named = list(named_parameters) if named_parameters is not None \
            else list(model.named_parameters())
        from collections import Counter
        dups = {n for n, c in Counter(k for k, _ in named).items() if c > 1}
        if dups:
            raise ValueError(f"duplicate parameter names: {sorted(dups)}")
        self._half_params: List[torch.Tensor] = [p for _, p in named]
        self._names: Dict[int, str] = {id(p): n for n, p in named}
        # fp32 master copies (reference: fp32_params,
        # misc/imagenet18/__init__.py:90-97); the inner optimizer owns them.
        self._master_params: List[torch.nn.Parameter] = [
            torch.nn.Parameter(p.detach().float().clone())
            for p in self._half_params]
        self._inner = optimizer_factory(self._master_params)
        # Loss scaling (reference: static loss_scale; here also "dynamic").
        self._dynamic = loss_scale == "dynamic"
        self._scale = 2.0 ** 16 if self._dynamic else float(loss_scale)
        self._growth_interval = scale_growth_interval
        self._good_steps = 0
        self.steps_skipped = 0  # overflow-skipped steps (introspection)

    # -- loss scaling -------------------------------------------------------
    @property
    def loss_scale(self) -> float:
        return self._scale

    def scale_loss(self, loss: torch.Tensor) -> torch.Tensor:
        return loss * self._scale

    # -- optimizer surface --------------------------------------------------
    def zero_grad(self, set_to_none: bool = True) -> None:
        for p in self._half_params:
            if p.grad is not None:
                if set_to_none:
                    p.grad = None
                else:
                    p.grad.zero_()

    @property
    def param_groups(self):
        return self._inner.param_groups

    def state_dict(self):
        return {"inner": self._inner.state_dict(), "scale": self._scale,
                "masters": [p.detach().clone()
                            for p in self._master_params]}

    def load_state_dict(self, sd):
        self._inner.load_state_dict(sd["inner"])
        self._scale = sd["scale"]
        with torch.no_grad():
            for m, saved in zip(self._master_params, sd["masters"]):
                m.copy_(saved)
        self._copy_masters_to_model()

    def step(self, closure=None) -> None:
        """push_pull the half-precision grads (compressed wire), unscale
        into the fp32 masters, step the inner optimizer, cast masters back
        (reference: misc/imagenet18/__init__.py:250-330)."""
        if closure is not None:
            raise ValueError("closure is not supported in fp16 mode")
        # Dispatch every gradient first (overlap), then synchronize.
        handles = []
        for p in self._half_params:
            if p.grad is None:
                continue
            name = "Gradient." + self._names[id(p)]
            h = push_pull_async(p.grad, average=True, name=name,
                                compression=self._compression)
            handles.append((p, h))
        for _p, h in handles:
            synchronize(h)
        # Unscale into masters; detect overflow for the dynamic scaler.
        inv = 1.0 / self._scale
        overflow = False
        with torch.no_grad():
            for half_p, master in zip(self._half_params,
                                      self._master_params):
                if half_p.grad is None:
                    master.grad = None
                    continue
                # copy=True: for params kept in fp32 (norm layers etc.)
                # .float() would alias p.grad and mul_ would mutate the
                # model's gradient in place.
                g32 = half_p.grad.detach().to(dtype=torch.float32,
                                              copy=True).mul_(inv)
                if not torch.isfinite(g32).all():
                    overflow = True
                master.grad = g32
        if overflow:
            self.steps_skipped += 1
            if self._dynamic:
                self._scale = max(self._scale / 2.0, 1.0)
                self._good_steps = 0
            return  # skip the update entirely, matching AMP semantics
        self._inner.step()
        if self._dynamic:
            self._good_steps += 1
            if self._good_steps >= self._growth_interval:
                self._scale *= 2.0
                self._good_steps = 0
        self._copy_masters_to_model()

    def _copy_masters_to_model(self) -> None:
        with torch.no_grad():
            for half_p, master in zip(self._half_params,
                                      self._master_params):
                half_p.copy_(master.to(half_p.dtype))


def broadcast_fp16_parameters(opt: HalfPrecisionDistributedOptimizer,
                              root_rank: int = 0) -> None:
    """Broadcast the fp32 masters AND the half model params from root so all
    workers start bit-identical (the reference broadcasts the model and
    relies on masters being derived from it)."""
    from . import broadcast_parameters
    if size() == 1:
        return
    broadcast_parameters(
        {f"master.{i}": p for i, p in enumerate(opt._master_params)},
        root_rank)
    opt._copy_masters_to_model()
