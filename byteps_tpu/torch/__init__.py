"""PyTorch plugin: the reference's torch API surface on the TPU framework.

Mirrors byteps.torch (reference: byteps/torch/__init__.py:23-28,
torch/ops.py:157-236): `init/shutdown`, `rank/size`, `push_pull(_async)/
synchronize/poll`, `DistributedOptimizer`, `broadcast_parameters/
broadcast_optimizer_state`, `DistributedDataParallel` — so training
scripts written for the reference port by changing the import.

Execution model: torch tensors live on host; communication rides the
framework's eager push_pull (XLA collectives across JAX processes, or the
PS tier under BYTEPS_TPU_PS_MODE).  Gradient communication for a step is
launched async for every parameter first (the backward-hook overlap of the
reference collapses into JAX async dispatch) and synchronized before the
inner optimizer step.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Optional, Tuple

import numpy as np
import torch

from ..common import api as _api
from ..ops.compression import Compression

# Lifecycle / topology re-exports (reference: common/__init__.py:52-139)
init = _api.init
shutdown = _api.shutdown
suspend = _api.suspend
resume = _api.resume
rank = _api.rank
size = _api.size
local_rank = _api.local_rank
local_size = _api.local_size
declare = _api.declare
get_pushpull_speed = _api.get_pushpull_speed


_handles: Dict[int, Tuple[torch.Tensor, bool]] = {}


def _to_jax(t: torch.Tensor):
    import jax.numpy as jnp
    return jnp.asarray(t.detach().cpu().numpy())


def _from_jax(a, like: torch.Tensor) -> torch.Tensor:
    return torch.from_numpy(np.asarray(a)).to(dtype=like.dtype,
                                              device=like.device)


def push_pull_async(tensor: torch.Tensor, average: bool = True,
                    name: Optional[str] = None,
                    priority: int = 0, compression=Compression.none) -> int:
    """Non-blocking in-place push_pull; returns a handle for synchronize()
    (reference: torch/ops.py:157-186)."""
    h = _api.push_pull_async(_to_jax(tensor), name=name, average=average,
                             priority=priority, compression=compression)
    _handles[h] = (tensor, average)
    return h


def push_pull_async_inplace(tensor, average=True, name=None, priority=0):
    return push_pull_async(tensor, average=average, name=name,
                           priority=priority)


def push_pull(tensor: torch.Tensor, average: bool = True,
              name: Optional[str] = None, priority: int = 0,
              compression=Compression.none) -> torch.Tensor:
    """Blocking push_pull; returns a new tensor (reference:
    torch/ops.py:188-206)."""
    h = push_pull_async(tensor, average=average, name=name,
                        priority=priority, compression=compression)
    return synchronize(h)


def synchronize(handle: int) -> torch.Tensor:
    """Wait for an async push_pull; writes the result back in place and
    returns the tensor (reference: torch/ops.py:222-236)."""
    tensor, _ = _handles.pop(handle)
    out = _api.synchronize(handle)
    result = _from_jax(out, tensor)
    with torch.no_grad():
        tensor.copy_(result)
    return tensor


def poll(handle: int) -> bool:
    return _api.poll(handle)


class _DistributedOptimizer(torch.optim.Optimizer):
    """Wraps a torch optimizer so step() averages gradients across workers
    first (reference: torch/__init__.py:115-214)."""

    def __init__(self, optimizer: torch.optim.Optimizer, named_parameters,
                 compression, backward_passes_per_step: int = 1,
                 enable_async: bool = False):
        self._inner = optimizer
        self._compression = compression
        self._bpps = backward_passes_per_step
        self._enable_async = enable_async
        self._async_keys: Dict[int, int] = {}  # id(param) -> declared key
        if named_parameters is not None:
            named = list(named_parameters)
        else:
            named = [(f"param.{i}.{j}", p)
                     for i, g in enumerate(optimizer.param_groups)
                     for j, p in enumerate(g["params"])]
        self._names = {p: n for n, p in named}
        # expose inner state so schedulers etc. keep working
        self.param_groups = optimizer.param_groups
        self.defaults = optimizer.defaults
        self.state = optimizer.state

    def step(self, closure=None):
        if self._enable_async:
            return self._step_async(closure)
        # The whole gradient list travels as ONE batched collective (one
        # host crossing, one all-reduce-shaped wire transfer) instead of a
        # per-tensor allgather round-trip each — the reference's DDP
        # gradient batching stance (torch/parallel/distributed.py:235-243).
        grads: Dict[str, Any] = {}
        by_name: Dict[str, torch.Tensor] = {}
        for group in self.param_groups:
            for p in group["params"]:
                if p.grad is None:
                    continue
                name = "Gradient." + self._names.get(p, f"anon.{id(p)}")
                grads[name] = _to_jax(p.grad)
                by_name[name] = p.grad
        if grads:
            out = _api.push_pull_tree(grads, average=True,
                                      compression=self._compression,
                                      leaf_names=sorted(grads))
            with torch.no_grad():
                for name, g in by_name.items():
                    g.copy_(_from_jax(out[name], g))
        if self._bpps > 1:
            for group in self.param_groups:
                for p in group["params"]:
                    if p.grad is not None:
                        p.grad.div_(self._bpps)
        return self._inner.step(closure)

    def _step_async(self, closure):
        """Async PS mode: run the local optimizer step, push the weight
        DELTA, adopt the server's global weights (reference:
        torch/__init__.py:186-214 under BYTEPS_ENABLE_ASYNC)."""
        sess = _api.get_ps_session()
        if sess is None or not getattr(sess, "server_async", False):
            raise RuntimeError(
                "enable_async requires BYTEPS_TPU_PS_MODE=1 with servers "
                "running BYTEPS_ENABLE_ASYNC=1")
        params = [p for g in self.param_groups for p in g["params"]]
        for p in params:
            if id(p) in self._async_keys:
                continue
            # Seed each (possibly late-added) param's store with its
            # current weights (apply-only-if-untouched, so late joiners
            # adopt live weights instead of resetting them).
            name = "AsyncParam." + self._names.get(p, f"anon.{id(p)}")
            dk = _api.declare(name)
            self._async_keys[id(p)] = dk
            got = sess.push_pull(dk, p.detach().cpu().numpy(), seed=True)
            with torch.no_grad():
                p.copy_(_from_jax(got, p))
        if self._bpps > 1:
            # Same accumulated-gradient normalization as the sync path.
            for p in params:
                if p.grad is not None:
                    p.grad.div_(self._bpps)
        old = {id(p): p.detach().clone() for p in params}
        loss = self._inner.step(closure)
        # Dispatch every delta through the session's priority-scheduled
        # dispatcher first, then adopt — overlapping the per-param
        # round-trips instead of serializing N RTTs.
        handles = []
        for p in params:
            delta = (p.detach() - old[id(p)]).cpu().numpy()
            handles.append(
                (p, sess.push_pull_async(self._async_keys[id(p)], delta)))
        for p, h in handles:
            with torch.no_grad():
                p.copy_(_from_jax(h.wait(), p))
        return loss

    def zero_grad(self, set_to_none: bool = True):
        return self._inner.zero_grad(set_to_none=set_to_none)

    def state_dict(self):
        return self._inner.state_dict()

    def load_state_dict(self, sd):
        return self._inner.load_state_dict(sd)


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         enable_async: Optional[bool] = None):
    """enable_async=None reads BYTEPS_ENABLE_ASYNC, matching the reference's
    env-driven switch (reference: torch/__init__.py:432-446)."""
    if enable_async is None:
        from ..common.config import get_config
        enable_async = get_config().enable_async
    return _DistributedOptimizer(optimizer, named_parameters, compression,
                                 backward_passes_per_step, enable_async)


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """In-place broadcast of a state_dict or iterable of (name, tensor)
    (reference: torch/__init__.py:259-291).

    All tensors travel in ONE tree broadcast (a single host->device->host
    round-trip) instead of one collective per tensor — the host round-trip
    is the torch plugin's tax for living outside XLA, so it is paid once.
    """
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = list(params)
    tensors = {name: t for name, t in items if torch.is_tensor(t)}
    if not tensors:
        return
    out = _api.broadcast_parameters(
        {name: _to_jax(t) for name, t in tensors.items()}, root_rank)
    with torch.no_grad():
        for name, t in tensors.items():
            t.copy_(_from_jax(out[name], t))


def broadcast_optimizer_state(optimizer: torch.optim.Optimizer,
                              root_rank: int = 0) -> None:
    """Broadcast optimizer state tensors AND scalar hyper-state
    (reference: torch/__init__.py:293-409 tensor-izes scalars).  Like
    broadcast_parameters, everything ships in one tree broadcast."""
    sd = optimizer.state_dict()
    tree = {}
    for pid, pstate in sd.get("state", {}).items():
        for k, v in pstate.items():
            if torch.is_tensor(v):
                tree[(pid, k)] = _to_jax(v)
            elif isinstance(v, (int, float)):
                tree[(pid, k)] = _to_jax(torch.tensor(float(v)))
    if not tree:
        return
    # dict keys must be hashable+sortable for the pytree: encode as strings.
    enc = {f"{pid}::{k}": v for (pid, k), v in tree.items()}
    out = _api.broadcast_parameters(enc, root_rank)
    for pid, pstate in sd.get("state", {}).items():
        for k, v in list(pstate.items()):
            got = out.get(f"{pid}::{k}")
            if got is None:
                continue
            if torch.is_tensor(v):
                with torch.no_grad():
                    v.copy_(_from_jax(got, v))
            elif isinstance(v, (int, float)):
                pstate[k] = type(v)(np.asarray(got).item())
    optimizer.load_state_dict(sd)


class DistributedDataParallel(torch.nn.Module):
    """DDP wrapper: broadcasts module state at construction, re-broadcasts
    buffers each forward, and — like the reference — fires the gradient
    synchronization automatically when the backward pass completes
    (reference: torch/parallel/distributed.py:235-243 counts grads per
    backward and synchronizes on the final one), so plain
    `loss.backward(); optimizer.step()` works with no explicit
    synchronize() and no DistributedOptimizer.

    Use a PLAIN optimizer with auto_sync (the default): combining it with
    DistributedOptimizer would all-reduce every gradient twice per step —
    numerically harmless (re-averaging an average) but it doubles the
    communication bill.  Pass auto_sync=False to manage synchronization
    yourself or through DistributedOptimizer."""

    def __init__(self, module: torch.nn.Module, broadcast_buffers=True,
                 auto_sync: bool = True):
        super().__init__()
        self.module = module
        self.broadcast_buffers = broadcast_buffers
        self.auto_sync = auto_sync
        self.autosync_count = 0  # completed auto-syncs (introspection)
        broadcast_parameters(self.module.state_dict(), root_rank=0)
        self._backward_cb_queued = False
        if auto_sync:
            for p in self.module.parameters():
                if p.requires_grad:
                    p.register_post_accumulate_grad_hook(self._grad_hook)

    def _grad_hook(self, _param) -> None:
        # The first grad of a backward queues an end-of-backward engine
        # callback; the engine runs it after the WHOLE backward graph
        # finishes, so the sync fires exactly once per backward even when
        # some parameters never receive a gradient this pass (conditional
        # branches / partial graphs — counting hooks against the full
        # parameter set would desynchronize permanently there).  The
        # reference counts hooks (torch/parallel/distributed.py:235-243)
        # and shares torch-DDP's unused-parameter caveat; the engine
        # callback removes it.
        if not self._backward_cb_queued:
            self._backward_cb_queued = True
            torch.autograd.Variable._execution_engine.queue_callback(
                self._on_backward_end)

    def _on_backward_end(self) -> None:
        self._backward_cb_queued = False
        self.synchronize()
        self.autosync_count += 1

    def forward(self, *args, **kwargs):
        # A backward that raised after hooks fired leaves the engine's
        # final-callback queue dropped and the flag stuck; re-arm here so
        # auto-sync survives a caught exception instead of silently
        # disabling itself for the rest of training.
        self._backward_cb_queued = False
        if self.broadcast_buffers and size() > 1:
            broadcast_parameters(dict(self.module.named_buffers()),
                                 root_rank=0)
        return self.module(*args, **kwargs)

    def synchronize(self) -> None:
        grads = {f"DDP.Gradient.{n}": _to_jax(p.grad)
                 for n, p in self.module.named_parameters()
                 if p.grad is not None}
        if not grads:
            return
        # One batched collective for the whole list (see
        # _DistributedOptimizer.step).
        out = _api.push_pull_tree(grads, average=True,
                                  leaf_names=sorted(grads))
        with torch.no_grad():
            for n, p in self.module.named_parameters():
                key = f"DDP.Gradient.{n}"
                if key in out:
                    p.grad.copy_(_from_jax(out[key], p.grad))


# fp16 wire + fp32 master-weight training (reference: misc/imagenet18).
# Imported last: fp16.py imports this module's push_pull surface.
from .fp16 import (  # noqa: E402
    HalfPrecisionDistributedOptimizer, broadcast_fp16_parameters)
# Cross-barrier (ByteScheduler) — same deferred-import reason.
from .cross_barrier import CrossBarrier  # noqa: E402
