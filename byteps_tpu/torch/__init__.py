"""PyTorch plugin: the reference's torch API surface on the TPU framework.

Mirrors byteps.torch (reference: byteps/torch/__init__.py:23-28,
torch/ops.py:157-236): `init/shutdown`, `rank/size`, `push_pull(_async)/
synchronize/poll`, `DistributedOptimizer`, `broadcast_parameters/
broadcast_optimizer_state`, `DistributedDataParallel` — so training
scripts written for the reference port by changing the import.

Execution model: torch tensors live on host; communication rides the
framework's eager push_pull (XLA collectives across JAX processes, or the
PS tier under BYTEPS_TPU_PS_MODE).  Gradient communication for a step is
launched async for every parameter first (the backward-hook overlap of the
reference collapses into JAX async dispatch) and synchronized before the
inner optimizer step.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Optional, Tuple

import numpy as np
import torch

from ..common import api as _api
from ..ops.compression import Compression

# Lifecycle / topology re-exports (reference: common/__init__.py:52-139)
init = _api.init
shutdown = _api.shutdown
suspend = _api.suspend
resume = _api.resume
rank = _api.rank
size = _api.size
local_rank = _api.local_rank
local_size = _api.local_size
declare = _api.declare
get_pushpull_speed = _api.get_pushpull_speed


_handles: Dict[int, Tuple[torch.Tensor, bool]] = {}


def _to_jax(t: torch.Tensor):
    import jax.numpy as jnp
    return jnp.asarray(t.detach().cpu().numpy())


def _from_jax(a, like: torch.Tensor) -> torch.Tensor:
    return torch.from_numpy(np.asarray(a)).to(dtype=like.dtype,
                                              device=like.device)


def push_pull_async(tensor: torch.Tensor, average: bool = True,
                    name: Optional[str] = None,
                    priority: int = 0, compression=Compression.none) -> int:
    """Non-blocking in-place push_pull; returns a handle for synchronize()
    (reference: torch/ops.py:157-186)."""
    h = _api.push_pull_async(_to_jax(tensor), name=name, average=average,
                             priority=priority, compression=compression)
    _handles[h] = (tensor, average)
    return h


def push_pull_async_inplace(tensor, average=True, name=None, priority=0):
    return push_pull_async(tensor, average=average, name=name,
                           priority=priority)


def push_pull(tensor: torch.Tensor, average: bool = True,
              name: Optional[str] = None, priority: int = 0,
              compression=Compression.none) -> torch.Tensor:
    """Blocking push_pull; returns a new tensor (reference:
    torch/ops.py:188-206)."""
    h = push_pull_async(tensor, average=average, name=name,
                        priority=priority, compression=compression)
    return synchronize(h)


def synchronize(handle: int) -> torch.Tensor:
    """Wait for an async push_pull; writes the result back in place and
    returns the tensor (reference: torch/ops.py:222-236)."""
    tensor, _ = _handles.pop(handle)
    out = _api.synchronize(handle)
    result = _from_jax(out, tensor)
    with torch.no_grad():
        tensor.copy_(result)
    return tensor


def poll(handle: int) -> bool:
    return _api.poll(handle)


class _DistributedOptimizer(torch.optim.Optimizer):
    """Wraps a torch optimizer so step() averages gradients across workers
    first (reference: torch/__init__.py:115-214)."""

    def __init__(self, optimizer: torch.optim.Optimizer, named_parameters,
                 compression, backward_passes_per_step: int = 1):
        self._inner = optimizer
        self._compression = compression
        self._bpps = backward_passes_per_step
        if named_parameters is not None:
            named = list(named_parameters)
        else:
            named = [(f"param.{i}.{j}", p)
                     for i, g in enumerate(optimizer.param_groups)
                     for j, p in enumerate(g["params"])]
        self._names = {p: n for n, p in named}
        # expose inner state so schedulers etc. keep working
        self.param_groups = optimizer.param_groups
        self.defaults = optimizer.defaults
        self.state = optimizer.state

    def step(self, closure=None):
        handles = []
        for group in self.param_groups:
            for p in group["params"]:
                if p.grad is None:
                    continue
                name = "Gradient." + self._names.get(p, f"anon.{id(p)}")
                h = push_pull_async(p.grad, average=True, name=name,
                                    compression=self._compression)
                handles.append(h)
        for h in handles:
            synchronize(h)
        if self._bpps > 1:
            for group in self.param_groups:
                for p in group["params"]:
                    if p.grad is not None:
                        p.grad.div_(self._bpps)
        return self._inner.step(closure)

    def zero_grad(self, set_to_none: bool = True):
        return self._inner.zero_grad(set_to_none=set_to_none)

    def state_dict(self):
        return self._inner.state_dict()

    def load_state_dict(self, sd):
        return self._inner.load_state_dict(sd)


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1):
    return _DistributedOptimizer(optimizer, named_parameters, compression,
                                 backward_passes_per_step)


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """In-place broadcast of a state_dict or iterable of (name, tensor)
    (reference: torch/__init__.py:259-291)."""
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = list(params)
    for name, t in items:
        if not torch.is_tensor(t):
            continue
        out = _api.broadcast_parameters(_to_jax(t), root_rank)
        with torch.no_grad():
            t.copy_(_from_jax(out, t))


def broadcast_optimizer_state(optimizer: torch.optim.Optimizer,
                              root_rank: int = 0) -> None:
    """Broadcast optimizer state tensors AND scalar hyper-state
    (reference: torch/__init__.py:293-409 tensor-izes scalars)."""
    sd = optimizer.state_dict()
    for pid, pstate in sd.get("state", {}).items():
        for k, v in list(pstate.items()):
            if torch.is_tensor(v):
                out = _api.broadcast_parameters(_to_jax(v), root_rank)
                with torch.no_grad():
                    v.copy_(_from_jax(out, v))
            elif isinstance(v, (int, float)):
                t = torch.tensor(float(v))
                out = _api.broadcast_parameters(_to_jax(t), root_rank)
                pstate[k] = type(v)(np.asarray(out).item())
    optimizer.load_state_dict(sd)


class DistributedDataParallel(torch.nn.Module):
    """Minimal DDP wrapper: broadcasts module state at construction,
    re-broadcasts buffers each forward, averages gradients in
    `synchronize()` (reference: torch/parallel/distributed.py — the
    backward-hook auto-sync there maps to calling synchronize() before
    optimizer.step(), which DistributedOptimizer already does; this wrapper
    exists for API parity and buffer consistency)."""

    def __init__(self, module: torch.nn.Module, broadcast_buffers=True):
        super().__init__()
        self.module = module
        self.broadcast_buffers = broadcast_buffers
        broadcast_parameters(self.module.state_dict(), root_rank=0)

    def forward(self, *args, **kwargs):
        if self.broadcast_buffers and size() > 1:
            broadcast_parameters(dict(self.module.named_buffers()),
                                 root_rank=0)
        return self.module(*args, **kwargs)

    def synchronize(self) -> None:
        handles = [push_pull_async(p.grad, average=True,
                                   name=f"DDP.Gradient.{n}")
                   for n, p in self.module.named_parameters()
                   if p.grad is not None]
        for h in handles:
            synchronize(h)
