"""Torch cross-barrier: overlap gradient sync with the NEXT step's forward.

The reference removes the global synchronization barrier inside the torch
optimizer (ByteScheduler; reference: byteps/torch/cross_barrier.py:28-231):
backward hooks dispatch each gradient's push_pull immediately, a poller
thread completes them out-of-band and applies a PER-PARAMETER optimizer
update the moment that gradient arrives, and forward pre-hooks on each
module block on per-parameter locks — so step N+1's forward for early
layers runs while step N's late-layer gradients are still in flight.

TPU-native redesign, not a port:
  - per-parameter updates use a private single-parameter instance of the
    caller's OWN optimizer class (same hyperparameters), so ANY torch
    optimizer works — the reference re-implements SGD/Adam/RMSprop by hand
    and rejects everything else (cross_barrier.py:159-186).
  - gradient hooks use `register_post_accumulate_grad_hook` (the public
    engine API) instead of reaching into `grad_fn.next_functions`.
  - communication is the framework's eager handle API (XLA collective or
    PS tier), injected as `comm=(dispatch, wait)` so tests can shape the
    completion timeline deterministically.

The JAX-plane counterpart (bucketed collectives overlapped by async
dispatch) is parallel/cross_barrier.py; this module is the torch-plugin
parity surface.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, Optional, Tuple

import torch

from ..ops.compression import Compression


class _CrossBarrierOptimizer:
    """Optimizer facade whose updates are applied per-parameter by a poller
    thread as each gradient's push_pull completes."""

    def __init__(self, model: torch.nn.Module,
                 optimizer: torch.optim.Optimizer,
                 named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step: int = 1,
                 comm: Optional[Tuple[Callable, Callable]] = None):
        from . import poll, push_pull_async, synchronize  # eager surface
        self._model = model
        self._inner = optimizer
        self._compression = compression
        self._bpps = max(1, backward_passes_per_step)
        self._dispatch = comm[0] if comm else (
            lambda p, name: push_pull_async(p.grad, average=True, name=name,
                                            compression=compression))
        self._wait = comm[1] if comm else synchronize
        self._poll = (comm[2] if comm and len(comm) > 2
                      else (poll if comm is None else (lambda h: True)))
        if named_parameters is not None:
            self._names = {p: n for n, p in named_parameters}
        else:
            self._names = {p: f"param.{i}.{j}"
                           for i, g in enumerate(optimizer.param_groups)
                           for j, p in enumerate(g["params"])}
        # Inner-state passthrough.  LR schedulers attach to the INNER
        # optimizer (this facade is not a torch.optim.Optimizer); the
        # groups are shared dicts and _apply_update re-reads them at every
        # per-param step, so schedule changes take effect immediately.
        self.param_groups = optimizer.param_groups
        self.defaults = optimizer.defaults
        self.state = optimizer.state

        # One single-parameter optimizer per param, same class + hypers:
        # the poller applies exactly the caller's algorithm, one tensor at
        # a time (the reference's per-param _sgd/_adam/_rmsprop, minus the
        # three-optimizer limitation).
        self._param_opt: Dict[torch.Tensor, torch.optim.Optimizer] = {}
        self._locks: Dict[torch.Tensor, threading.Lock] = {}
        self._accum: Dict[torch.Tensor, int] = {}
        import inspect
        ctor_args = set(
            inspect.signature(type(optimizer).__init__).parameters)
        self._src_group: Dict[torch.Tensor, dict] = {}
        for group in optimizer.param_groups:
            # Param groups can carry bookkeeping keys the constructor does
            # not accept (e.g. AdamW's decoupled_weight_decay) — keep only
            # real constructor hyperparameters.
            hyper = {k: v for k, v in group.items()
                     if k != "params" and k in ctor_args}
            for p in group["params"]:
                self._param_opt[p] = type(optimizer)([p], **hyper)
                self._src_group[p] = group  # live hypers (see _apply_update)
                self._locks[p] = threading.Lock()
                self._accum[p] = 0
        self.step_count = 0
        self._sync_events: "queue.Queue" = queue.Queue()
        self._errors: list = []
        self._closed = False

        self._hook_handles = []
        for p in self._param_opt:
            if p.requires_grad:
                self._hook_handles.append(
                    p.register_post_accumulate_grad_hook(self._grad_ready))
        self._install_forward_hooks()
        self._poller = threading.Thread(target=self._poll_loop, daemon=True,
                                        name="bps-cross-barrier")
        self._poller.start()

    # -- backward side ------------------------------------------------------
    def _grad_ready(self, p: torch.Tensor) -> None:
        """Engine hook: p's gradient for this backward is final — ship it."""
        self._accum[p] += 1
        if self._accum[p] < self._bpps:
            return
        self._accum[p] = 0
        if self._bpps > 1:
            with torch.no_grad():
                p.grad.div_(self._bpps)
        name = "CrossBarrier.Gradient." + self._names.get(p, f"anon.{id(p)}")
        self._locks[p].acquire()  # released by the poller after the update
        try:
            handle = self._dispatch(p, name)
        except Exception:
            self._locks[p].release()
            raise
        self._sync_events.put((p, handle))

    # -- poller side --------------------------------------------------------
    def _poll_loop(self) -> None:
        """Complete push_pulls out-of-band; apply that ONE parameter's
        update immediately; release its forward lock (reference:
        cross_barrier.py:159-186).  A handle that is not finished is
        REQUEUED, never blocked on — one slow gradient must not hold up
        the updates (and forward locks) of gradients that completed after
        it."""
        import time as _time
        stall_marker = None   # first requeued item of a no-progress cycle
        while True:
            item = self._sync_events.get()
            if item is None:
                return
            p, handle = item
            try:
                done = self._poll(handle)
            except Exception as e:
                self._errors.append(e)
                self._locks[p].release()
                continue
            if not done:                 # still in flight: lock stays held
                self._sync_events.put(item)
                if stall_marker is None:
                    stall_marker = item
                elif stall_marker is item:
                    # A full pass over the queue completed nothing — yield.
                    # (Sleeping per requeue would delay completed handles
                    # queued behind a pending one; never sleeping would
                    # hot-spin a core for the whole comm latency.)
                    _time.sleep(0.001)
                continue
            stall_marker = None          # progress: reset the cycle marker
            try:
                self._wait(handle)       # averaged grad lands in p.grad
                self._apply_update(p)
            except Exception as e:       # surfaced by step()/close()
                self._errors.append(e)
            finally:
                self._locks[p].release()

    def _apply_update(self, p: torch.Tensor) -> None:
        po = self._param_opt[p]
        # Re-read hyperparameters from the user's (shared) param_group at
        # every update: LR schedulers mutate group["lr"] on the inner
        # optimizer, and the per-param instance must see it — its
        # construction-time snapshot would otherwise freeze the schedule.
        src = self._src_group[p]
        po.param_groups[0].update(
            {k: v for k, v in src.items() if k != "params"})
        po.step()
        with torch.no_grad():
            p.grad.zero_()

    # -- forward side -------------------------------------------------------
    def _install_forward_hooks(self) -> None:
        """Every leaf module waits on its own parameters' locks before its
        forward — blocking exactly the layer whose update is still in
        flight while earlier layers run (reference:
        cross_barrier.py:188-222)."""
        def pre_forward(mod, _inputs):
            for p in mod.parameters(recurse=False):
                lk = self._locks.get(p)
                if lk is not None:
                    with lk:
                        pass
        for mod in self._model.modules():
            if next(mod.parameters(recurse=False), None) is not None:
                self._hook_handles.append(
                    mod.register_forward_pre_hook(pre_forward))

    # -- optimizer facade ---------------------------------------------------
    def step(self, closure=None) -> None:
        """A scheduling boundary, not a barrier: updates are applied by the
        poller; the next forward's pre-hooks enforce the dependencies."""
        del closure
        self.step_count += 1
        if self._errors:
            raise self._errors.pop(0)

    def zero_grad(self, set_to_none: bool = False) -> None:
        """No-op by design: the poller zeroes each grad right after its
        per-param update (set_to_none would race the poller's in-place
        writes)."""
        del set_to_none

    def synchronize(self) -> None:
        """Block until every in-flight gradient has been applied (end of
        training, or before checkpointing)."""
        for p, lk in self._locks.items():
            with lk:
                pass
        if self._errors:
            raise self._errors.pop(0)

    def state_dict(self) -> Dict[str, Any]:
        self.synchronize()
        return {"per_param": [o.state_dict()
                              for o in self._param_opt.values()],
                "step_count": self.step_count}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        for o, s in zip(self._param_opt.values(), sd["per_param"]):
            o.load_state_dict(s)
        self.step_count = sd.get("step_count", 0)

    def close(self) -> None:
        """Drain, stop the poller, and DETACH every hook this wrapper
        installed — a backward after close() would otherwise dispatch into
        a dead queue, leave its lock held forever, and deadlock the next
        forward on the still-installed pre-hook.  Teardown runs even when
        the drain re-raises a recorded comm error (close() must never be a
        half-done no-op on retry)."""
        if not self._closed:
            self._closed = True
            try:
                self.synchronize()
            finally:
                for h in self._hook_handles:
                    h.remove()
                self._hook_handles.clear()
                self._sync_events.put(None)
                self._poller.join(timeout=10)


def CrossBarrier(model: torch.nn.Module,
                 optimizer: torch.optim.Optimizer,
                 named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step: int = 1,
                 comm: Optional[Tuple[Callable, Callable]] = None
                 ) -> _CrossBarrierOptimizer:
    """Wrap `optimizer` so gradient sync crosses the step barrier
    (reference factory: cross_barrier.py:413-431 — same call shape)."""
    return _CrossBarrierOptimizer(model, optimizer, named_parameters,
                                  compression, backward_passes_per_step,
                                  comm)
