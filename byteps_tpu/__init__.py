"""byteps_tpu — a TPU-native distributed training framework with the
capabilities of BytePS (reference: /root/reference, ruipeterpan/byteps).

Public API mirrors the reference's Horovod-compatible plugin surface
(reference: byteps/torch/__init__.py:23-28) re-designed for JAX/XLA:

    import byteps_tpu as bps
    bps.init()
    opt = bps.DistributedOptimizer(optax.adam(1e-3))
    step = bps.build_train_step(loss_fn, opt, bps.get_mesh())
"""

from .version import __version__

from .common.api import (
    init, shutdown, suspend, resume,
    rank, size, local_rank, local_size,
    leave, get_membership, on_membership_change,
    get_ring, drain_ps_server,
    declare, declared_key, register_compressor, get_ps_session,
    push_pull, push_pull_async, push_pull_tree, push_pull_sparse,
    synchronize, poll,
    broadcast_parameters, broadcast_optimizer_state,
    get_pushpull_speed, get_codec_stats, get_fusion_stats,
    get_transport_stats, get_metrics, get_server_stats,
    get_health, get_audit, get_key_signals, get_diagnosis,
    get_tuner, get_hierarchy, get_autoscaler, get_fleet,
    get_device_profile,
    mark_step, current_step,
)
from .parallel.async_ps import AsyncPSTrainer
from .parallel.hierarchy import HierarchicalReducer, SliceGroup
from .parallel.server_opt import ServerOptTrainer
from .parallel.embedding import EmbeddingTable
from .ops.compression import Compression
from .ops import collectives
from .parallel.data_parallel import (
    DistributedOptimizer, DistributedGradientTransformation,
    distributed_gradient_transform, build_train_step,
)
from .parallel.mesh import (
    make_mesh, make_hierarchical_mesh, make_slice_mesh, get_mesh,
    set_mesh, reset_mesh,
)
from .parallel.cross_barrier import CrossBarrierDriver, run_cross_barrier
from .parallel.sharded import (
    build_sharded_train_step, shard_params, init_sharded,
    zero1_opt_specs, zero1_init, fsdp_param_specs, fsdp_init,
)
from .ops import compressor
from .ops import ring_attention


def __getattr__(name):
    # Lazy submodules (PEP 562): `models` pulls in flax and `callbacks`
    # optax schedules — processes that only run the server/launcher
    # shouldn't pay those imports.
    if name in ("models", "callbacks", "utils"):
        import importlib
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "__version__",
    "init", "shutdown", "suspend", "resume",
    "rank", "size", "local_rank", "local_size",
    "leave", "get_membership", "on_membership_change",
    "get_ring", "drain_ps_server",
    "declare", "declared_key", "register_compressor", "get_ps_session",
    "push_pull", "push_pull_async", "push_pull_tree", "push_pull_sparse",
    "synchronize",
    "poll", "AsyncPSTrainer", "ServerOptTrainer", "EmbeddingTable",
    "broadcast_parameters", "broadcast_optimizer_state",
    "get_pushpull_speed", "get_codec_stats", "get_fusion_stats",
    "get_transport_stats", "get_metrics", "get_server_stats",
    "get_health", "get_audit", "get_key_signals", "get_diagnosis",
    "get_tuner", "get_hierarchy", "get_autoscaler", "get_fleet",
    "get_device_profile",
    "HierarchicalReducer", "SliceGroup",
    "mark_step", "current_step",
    "Compression", "collectives",
    "DistributedOptimizer", "DistributedGradientTransformation",
    "distributed_gradient_transform", "build_train_step",
    "make_mesh", "make_hierarchical_mesh", "make_slice_mesh",
    "get_mesh", "set_mesh", "reset_mesh",
    "CrossBarrierDriver", "run_cross_barrier",
    "build_sharded_train_step", "shard_params", "init_sharded",
    "zero1_opt_specs", "zero1_init", "fsdp_param_specs", "fsdp_init",
    "compressor", "ring_attention", "models", "callbacks", "utils",
]
