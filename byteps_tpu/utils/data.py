"""Input pipeline helpers: sharding and device prefetch.

The reference delegates input loading to each framework's loader; on TPU
the input pipeline is a first-order performance concern (HBM is fed over
PCIe from the host), so the framework ships the two standard tools:

  - `shard_batch`: place a host batch onto the mesh with the batch dim
    split over the dp axis (one host->device transfer per local shard);
  - `prefetch_to_device`: run the host iterator ahead of the device so
    step N+1's transfer overlaps step N's compute.
"""

from __future__ import annotations

import collections
import itertools
from typing import Any, Iterable, Iterator, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PyTree = Any


def shard_batch(batch: PyTree, mesh: Mesh, axis_name: str = "dp") -> PyTree:
    """device_put a host batch with axis 0 sharded over `axis_name`."""
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def prefetch_to_device(iterator: Iterable, size: int = 2,
                       mesh: Optional[Mesh] = None,
                       axis_name: str = "dp") -> Iterator:
    """Wrap a host batch iterator so `size` batches are always in flight to
    the device.  With a mesh, batches are dp-sharded on the way."""
    queue: collections.deque = collections.deque()
    it = iter(iterator)

    def put(batch):
        if mesh is not None:
            return shard_batch(batch, mesh, axis_name)
        return jax.tree.map(jax.device_put, batch)

    for batch in itertools.islice(it, size):
        queue.append(put(batch))
    while queue:
        yield queue.popleft()
        for batch in itertools.islice(it, 1):
            queue.append(put(batch))


def host_shard(batch: PyTree, rank: Optional[int] = None,
               size: Optional[int] = None) -> PyTree:
    """Slice a GLOBAL host batch down to this process's contiguous rows.

    The multihost input pattern (reference analog: rank-sharded sampling,
    torch DistributedSampler in the reference's examples): every process
    produces the same global batch deterministically (or addresses the
    same storage) and keeps rows [rank·per, (rank+1)·per).

    rank/size default to `jax.process_index()`/`jax.process_count()` —
    deliberately NOT byteps rank(): `global_batch_from_local` assembles
    by JAX process order, so the slicing index must use the same
    coordinate system or the assembled global array is a silent row
    permutation (byteps rank can diverge via BYTEPS_GLOBAL_RANK).  Pass
    an explicit rank only if you also control the assembly order.
    """
    rank = jax.process_index() if rank is None else rank
    size = jax.process_count() if size is None else size

    def slc(x):
        n = x.shape[0]
        if n % size:
            raise ValueError(
                f"global batch dim {n} is not divisible by world size "
                f"{size}")
        per = n // size
        return x[rank * per:(rank + 1) * per]

    return jax.tree.map(slc, batch)


def global_batch_from_local(batch: PyTree, mesh: Mesh,
                            axis_name: str = "dp") -> PyTree:
    """Assemble a global, dp-sharded jax.Array from each process's LOCAL
    shard (the inverse hand-off of `host_shard`: load locally, train
    globally).  Wraps jax.make_array_from_process_local_data so the
    result is addressable by a jitted step over `mesh` with the batch dim
    sharded over `axis_name`."""
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, x),
        batch)


def synthetic_batches(make_batch, n: Optional[int] = None) -> Iterator:
    """Endless (or n-long) stream of `make_batch(i)` results — the pattern
    the reference's synthetic benchmarks use."""
    counter = itertools.count() if n is None else range(n)
    for i in counter:
        yield make_batch(i)
