"""Checkpoint save/restore.

The reference has no checkpointing of its own — worker consistency comes
from broadcast at start, persistence is left to the framework
(reference: docs/best-practice.md, SURVEY §5).  The TPU build ships the
missing piece as a thin orbax wrapper handling the distributed details:
only rank 0 writes (unless the checkpointer is multi-host-aware), every
rank restores, and restored state is broadcast for bit-identical workers.
"""

from __future__ import annotations

import os
from typing import Any, Optional

PyTree = Any


def _ckptr():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def save(path: str, state: PyTree, force: bool = True) -> None:
    """Write `state` (any pytree of arrays) to `path` from rank 0."""
    from ..common.api import rank
    if rank() != 0:
        return
    _ckptr().save(os.path.abspath(os.path.expanduser(path)), state,
                  force=force)


def restore(path: str, template: Optional[PyTree] = None,
            broadcast: bool = True) -> PyTree:
    """Load the checkpoint at `path`; with `broadcast` (default) the result
    is broadcast from rank 0 so all workers start bit-identical — the same
    consistency contract the reference gets from broadcast_parameters
    (reference: torch/__init__.py:259-291)."""
    import jax
    restored = _ckptr().restore(os.path.abspath(os.path.expanduser(path)))
    if template is not None:
        # orbax returns dicts for any pytree; restore the caller's structure.
        leaves = jax.tree.leaves(restored)
        restored = jax.tree.unflatten(jax.tree.structure(template), leaves)
    if broadcast:
        from ..common.api import broadcast_parameters, size
        if size() > 1:
            restored = broadcast_parameters(restored, root_rank=0)
    return restored


def latest_step_dir(root: str) -> Optional[str]:
    """Convenience for step-numbered checkpoint layouts: returns the path
    of the highest-numbered subdirectory of `root`, or None."""
    if not os.path.isdir(root):
        return None
    steps = [d for d in os.listdir(root) if d.isdigit()]
    if not steps:
        return None
    return os.path.join(root, max(steps, key=int))
