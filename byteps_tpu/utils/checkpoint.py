"""Checkpoint save/restore.

The reference has no checkpointing of its own — worker consistency comes
from broadcast at start, persistence is left to the framework
(reference: docs/best-practice.md, SURVEY §5).  The TPU build ships the
missing piece as a thin orbax wrapper handling the distributed details:
only rank 0 writes (unless the checkpointer is multi-host-aware), every
rank restores, and restored state is broadcast for bit-identical workers.
"""

from __future__ import annotations

import os
from typing import Any, Optional

PyTree = Any


def _ckptr():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def _should_write() -> bool:
    """Single write-gate for sync and async savers.

    Under a live `jax.distributed` cluster EVERY process must write
    (orbax coordinates internally with global barriers; a rank-0-only
    call would deadlock the barrier).  Outside it — env-based clusters
    like PS mode, where processes share storage but not a JAX
    coordinator — only rank 0 writes."""
    import jax
    if jax.process_count() > 1:
        return True
    from ..common.api import rank
    return rank() == 0


def save(path: str, state: PyTree, force: bool = True) -> None:
    """Write `state` (any pytree of arrays) to `path` (see _should_write
    for the distributed gating contract)."""
    if not _should_write():
        return
    apath = os.path.abspath(os.path.expanduser(path))
    _ckptr().save(apath, state, force=force)


def restore(path: str, template: Optional[PyTree] = None,
            broadcast: bool = True) -> PyTree:
    """Load the checkpoint at `path`; with `broadcast` (default) the result
    is broadcast from rank 0 so all workers start bit-identical — the same
    consistency contract the reference gets from broadcast_parameters
    (reference: torch/__init__.py:259-291).

    When the template's leaves are jax.Arrays, each leaf restores with
    the TEMPLATE's sharding (orbax restore_args), not the sharding
    recorded in the checkpoint file — so a run saved on one mesh resumes
    correctly on a different topology (elastic resize, the reference's
    suspend/resume scenario), and sharded (FSDP/ZeRO) state restores
    partitioned without ever materializing replicated."""
    import jax

    apath = os.path.abspath(os.path.expanduser(path))
    if template is not None:
        # Hand orbax the template so it restores directly into the caller's
        # structure.  (Zipping restored leaves into the template's treedef
        # would silently permute leaves whenever orbax's container flatten
        # order differs from the template's — e.g. >=10 tuple entries
        # restored as string-keyed dicts sort "10" before "2".)
        # Without restore_args orbax repopulates shardings from the
        # file — stale device assignments when the mesh changed between
        # save and restore.  construct_restore_args handles mixed trees
        # per-leaf (jax.Arrays get their sharding, numpy/scalar leaves
        # plain RestoreArgs), so no all-or-nothing guard.
        from orbax.checkpoint import checkpoint_utils
        restore_args = checkpoint_utils.construct_restore_args(template)
        restored = _ckptr().restore(apath, item=template,
                                    restore_args=restore_args)
    else:
        restored = _ckptr().restore(apath)
    if broadcast:
        from ..common.api import broadcast_parameters, size
        # Broadcast exists for env-based clusters (PS mode) where ranks
        # share storage but not a JAX coordinator.  Multi-host GLOBAL
        # arrays (sharded restore under jax.distributed) are already
        # coordinated by orbax, and broadcast_one_to_all requires fully
        # addressable inputs — skip them.
        if size() > 1 and all(
                getattr(l, "is_fully_addressable", True)
                for l in jax.tree.leaves(restored)):
            restored = broadcast_parameters(restored, root_rank=0)
    return restored


class AsyncSaver:
    """Non-blocking checkpoint writes: save() returns as soon as the state
    is snapshotted; serialization/IO overlaps the next training steps.

    Beyond-reference (the reference leaves persistence to the framework);
    on TPU the win is real — a synchronous multi-GB write stalls the step
    loop for seconds.  Wraps orbax's AsyncCheckpointer; under a live
    jax.distributed cluster every process must call save()/wait() (orbax
    coordinates internally), mirroring `save` above.

        saver = AsyncSaver()
        saver.save(path, state)   # returns quickly
        ...training continues...
        saver.wait()              # barrier before shutdown/next save
    """

    def __init__(self):
        import orbax.checkpoint as ocp
        self._ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())

    def save(self, path: str, state: PyTree, force: bool = True) -> None:
        if not _should_write():
            return
        apath = os.path.abspath(os.path.expanduser(path))
        self._ckptr.save(apath, state, force=force)

    def wait(self) -> None:
        """Block until the in-flight save (if any) is durably on disk."""
        self._ckptr.wait_until_finished()

    def close(self) -> None:
        self.wait()
        self._ckptr.close()


def latest_step_dir(root: str) -> Optional[str]:
    """Convenience for step-numbered checkpoint layouts: returns the path
    of the highest-numbered subdirectory of `root`, or None."""
    if not os.path.isdir(root):
        return None
    steps = [d for d in os.listdir(root) if d.isdigit()]
    if not steps:
        return None
    return os.path.join(root, max(steps, key=int))
