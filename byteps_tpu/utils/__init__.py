"""Utilities: checkpointing (orbax wrapper) and input-pipeline helpers."""

from . import checkpoint
from . import data
from .data import shard_batch, prefetch_to_device, synthetic_batches

__all__ = ["checkpoint", "data", "shard_batch", "prefetch_to_device",
           "synthetic_batches"]
