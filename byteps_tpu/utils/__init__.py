"""Utilities: checkpointing (orbax wrapper) and input-pipeline helpers."""

from . import checkpoint
from . import data
from .data import (shard_batch, prefetch_to_device, synthetic_batches,
                   host_shard, global_batch_from_local)

__all__ = ["checkpoint", "data", "shard_batch", "prefetch_to_device",
           "synthetic_batches", "host_shard", "global_batch_from_local"]
