"""Hermetic CPU subprocess environments.

On TPU-attached hosts, setting JAX_PLATFORMS=cpu is NOT enough to keep a
child process off the accelerator: site hooks that register an external
PJRT plugin (gated on their own env vars, e.g. PALLAS_AXON_POOL_IPS)
force the platform selection back to the device, and a pure-CPU child
then blocks on real-device initialization — indefinitely, if the device
tunnel is unhealthy.  The gate vars must be stripped in the PARENT when
building the child's env; in-process deletion after interpreter startup
is too late (the site hook has already run).

Single source of truth for the gate-variable list; used by
tests/testutil.cpu_env, __graft_entry__.virtual_cpu_env, and
bench.bench_ps.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

# Env-var prefixes that gate accelerator-grabbing site hooks.
_ACCEL_GATE_PREFIXES = ("PALLAS_AXON", "AXON_")

# Direct TPU discovery vars.
_TPU_VARS = ("TPU_NAME", "TPU_WORKER_ID", "CLOUD_TPU_TASK_ID")


def strip_accelerator_gates(env: Dict[str, str]) -> Dict[str, str]:
    """Remove accelerator-hook gate vars from `env`, in place; returns it."""
    for k in list(env):
        if k.startswith(_ACCEL_GATE_PREFIXES) or k in _TPU_VARS:
            env.pop(k)
    return env


def cpu_subprocess_env(extra: Optional[Dict[str, str]] = None,
                       base: Optional[Dict[str, str]] = None
                       ) -> Dict[str, str]:
    """A copy of `base` (default os.environ) hermetically pinned to CPU."""
    env = strip_accelerator_gates(dict(os.environ if base is None else base))
    env["JAX_PLATFORMS"] = "cpu"
    if extra:
        env.update(extra)
    return env


def force_host_device_count(env: Dict[str, str], n: int) -> Dict[str, str]:
    """Pin XLA_FLAGS in `env` to exactly `n` virtual host devices, in place.

    Replaces any existing --xla_force_host_platform_device_count flag
    (appending blindly would leave two copies and XLA honors the first).
    """
    import re

    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}").strip()
    return env
