"""TPU-native transformer language model (the flagship model family).

The reference's headline benchmark is BERT-large data-parallel training
(reference: README.md:38-46 — ~90% scaling efficiency at 256 GPUs, GluonNLP
BERT via an external repo; the reference itself ships no model code).  This
module supplies the model the reference outsources: a pure-JAX transformer
encoder/decoder LM designed for the MXU —

  - all matmuls are (batch*seq, d_model) x (d_model, N) shaped, bf16 by
    default, so XLA tiles them onto the systolic array;
  - per-layer `jax.checkpoint` (rematerialisation) trades FLOPs for HBM;
  - params are a flat pytree of named arrays with an accompanying
    PartitionSpec tree (`param_specs`) giving Megatron-style tensor
    parallelism over the 'tp' mesh axis: QKV and MLP-in are column-sharded,
    attention-out and MLP-out row-sharded, everything else replicated;
  - layers are stacked with `lax.scan` over a single stacked param tree
    (compile time stays O(1) in depth, and the leading layer axis doubles as
    the pipeline-stage axis for 'pp').

Configs mirror the reference benchmark suite: bert_base/bert_large
(README.md:38-46) plus tiny variants for tests, and a llama-class decoder
family (RMSNorm + SwiGLU + RoPE + grouped-query attention, no biases) via
the norm/act/pos/num_kv_heads/use_bias knobs — the modern-LLM block on the
same stacked-scan machinery, so TP specs, pipeline stacking, remat, and
the flash/ring attention registry all apply unchanged.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

PyTree = Any


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32768
    num_layers: int = 12
    d_model: int = 768
    num_heads: int = 12
    d_ff: int = 3072
    max_seq_len: int = 512
    dtype: Any = jnp.bfloat16          # activation/compute dtype (MXU-native)
    param_dtype: Any = jnp.float32     # master params stay f32
    causal: bool = True                # decoder LM; False = BERT-style encoder
    # Modern-LLM (llama-class) architecture knobs.  Defaults reproduce the
    # classic BERT/GPT block exactly (same param tree, same math).
    norm: str = "layernorm"            # "layernorm" | "rmsnorm"
    act: str = "gelu"                  # "gelu" | "swiglu"
    pos: str = "learned"               # "learned" | "rope"
    rope_theta: float = 10000.0
    num_kv_heads: Optional[int] = None  # GQA/MQA: < num_heads; None = MHA
    use_bias: bool = True              # llama-class blocks drop biases
    remat: bool = True                 # per-layer rematerialisation
    # What the per-layer checkpoint may keep: "none" saves only layer
    # inputs (max recompute, min HBM); "dots" saves matmul outputs
    # (skips re-running the MXU work in backward but keeps the O(S²) and
    # O(4D) tensors — OOMs first at large batch); "dots_no_batch" drops
    # batch-dim-carrying dots; "proj" saves only the O(B·S·D) projection
    # outputs (qkv / attn ctx+proj / ffn down) and recomputes attention
    # logits + FFN-up in backward — fits where "dots" OOMs at large
    # batch while skipping most of full remat's recompute
    # (measurements: docs/performance.md).
    remat_policy: str = "none"    # "none" | "dots" | "dots_no_batch" | "proj"
    attn_impl: str = "dense"           # "dense" | "flash" | "ring" (sp)
    # Flash-kernel block size override (0 = flash_auto_block's measured
    # rule: full-sequence at S <= 512, largest of 512/256/128/64 dividing
    # S beyond).  Larger blocks at
    # short S mean fewer, fatter kernel programs; must divide seq_len.
    attn_block: int = 0
    # K/V tile override (0 = same as attn_block).  Decoupling lets long-S
    # sweeps trade per-iteration VMEM / causal masked waste (K tile)
    # against program count (Q tile) independently.
    attn_block_k: int = 0
    # Fused LM-head cross-entropy: > 0 streams the readout matmul + softmax
    # in row chunks of this size so the [B*S, vocab] logits are never
    # materialized (forward OR backward — each chunk is rematerialised).
    # 0 = classic path through full logits.  At bert_large bench scale the
    # full f32 logits are 3.2 GB and their HBM traffic is the largest
    # non-matmul cost in the step (round-3 profiling).
    ce_chunk_rows: int = 0
    # Unroll factor for the layer scan (lax.scan unroll=).  > 1 groups
    # that many layers per scan iteration: more code, but XLA can
    # schedule/fuse across adjacent layers and the stacked-param slice
    # overhead amortizes.  Remat granularity is unchanged (each layer
    # body is checkpointed individually).  Must divide num_layers or be
    # 1; sweep knob BENCH_UNROLL.
    scan_unroll: int = 1

    def __post_init__(self):
        for field, val, allowed in (
                ("norm", self.norm, ("layernorm", "rmsnorm")),
                ("act", self.act, ("gelu", "swiglu")),
                ("pos", self.pos, ("learned", "rope"))):
            if val not in allowed:
                # A typo here must not silently drop positions/gating.
                raise ValueError(f"{field}={val!r}; options: {allowed}")
        if self.d_model % self.num_heads:
            raise ValueError(f"d_model={self.d_model} not divisible by "
                             f"num_heads={self.num_heads}")
        if self.num_kv_heads is not None:
            if self.num_kv_heads < 1:
                raise ValueError("num_kv_heads must be >= 1 (or None for "
                                 "full multi-head attention)")
            if self.num_heads % self.num_kv_heads:
                raise ValueError(
                    f"num_heads={self.num_heads} not divisible by "
                    f"num_kv_heads={self.num_kv_heads} (GQA shares each kv "
                    f"head across an integer group of query heads)")
        if self.pos == "rope" and self.head_dim % 2:
            raise ValueError(f"pos='rope' needs an even head_dim "
                             f"(got {self.head_dim})")
        if self.ce_chunk_rows < 0:
            raise ValueError(f"ce_chunk_rows={self.ce_chunk_rows} must be "
                             f">= 0 (0 = unfused full-logits path)")
        if self.scan_unroll < 1 or self.num_layers % self.scan_unroll:
            raise ValueError(
                f"scan_unroll={self.scan_unroll} must be >= 1 and divide "
                f"num_layers={self.num_layers} (a remainder iteration "
                f"would compile a second layer-group program)")

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    @property
    def kv_heads(self) -> int:
        return (self.num_kv_heads if self.num_kv_heads is not None
                else self.num_heads)


# Benchmark-suite configs (reference README.md:38-46 benchmarks BERT-large;
# docs/performance.md benchmarks ResNet50/VGG16 — see models/cnn.py).
CONFIGS: Dict[str, TransformerConfig] = {
    "tiny": TransformerConfig(vocab_size=1024, num_layers=2, d_model=64,
                              num_heads=4, d_ff=128, max_seq_len=128),
    "bert_base": TransformerConfig(num_layers=12, d_model=768, num_heads=12,
                                   d_ff=3072, causal=False),
    "bert_large": TransformerConfig(num_layers=24, d_model=1024, num_heads=16,
                                    d_ff=4096, causal=False),
    "gpt_small": TransformerConfig(num_layers=12, d_model=768, num_heads=12,
                                   d_ff=3072, causal=True),
    "gpt_medium": TransformerConfig(num_layers=24, d_model=1024, num_heads=16,
                                    d_ff=4096, causal=True),
    # Llama-class decoder (RMSNorm + SwiGLU + RoPE + GQA, no biases) — the
    # modern-LLM block shape, at two scales.
    "llama_tiny": TransformerConfig(vocab_size=1024, num_layers=2, d_model=64,
                                    num_heads=4, num_kv_heads=2, d_ff=160,
                                    max_seq_len=128, norm="rmsnorm",
                                    act="swiglu", pos="rope", use_bias=False),
    "llama_1b": TransformerConfig(vocab_size=32768, num_layers=16,
                                  d_model=2048, num_heads=32, num_kv_heads=8,
                                  d_ff=5504, max_seq_len=2048, norm="rmsnorm",
                                  act="swiglu", pos="rope", use_bias=False),
    # ~300M-param llama geometry: the largest modern-LLM config whose f32
    # master weights + Adam moments (~4.8 GB) leave headroom for a real
    # batch at seq 2048 on one 16 GB chip — llama_1b's ~9.3 GB of
    # optimizer state OOMs the single-chip bench, so long-sequence
    # single-chip sweeps run here (multi-chip llama_1b shards the state).
    "llama_300m": TransformerConfig(vocab_size=32768, num_layers=24,
                                    d_model=1024, num_heads=16,
                                    num_kv_heads=4, d_ff=2816,
                                    max_seq_len=2048, norm="rmsnorm",
                                    act="swiglu", pos="rope", use_bias=False),
}


def get_config(name: str, **overrides) -> TransformerConfig:
    cfg = CONFIGS[name]
    return dataclasses.replace(cfg, **overrides) if overrides else cfg


# ---------------------------------------------------------------------------
# Parameter init.  Layer params are stacked along a leading num_layers axis.
# ---------------------------------------------------------------------------
def init_params(rng: jax.Array, cfg: TransformerConfig) -> PyTree:
    dt = cfg.param_dtype
    k_emb, k_pos, k_layers, k_out = jax.random.split(rng, 4)

    def dense_init(key, shape, fan_in):
        return (jax.random.normal(key, shape, dt) / jnp.sqrt(fan_in)).astype(dt)

    L, D, F = cfg.num_layers, cfg.d_model, cfg.d_ff
    Dh, Hkv = cfg.head_dim, cfg.kv_heads
    qkv_cols = (cfg.num_heads + 2 * Hkv) * Dh
    lkeys = jax.random.split(k_layers, 6)

    def stack(key, shape, fan_in):
        ks = jax.random.split(key, L)
        return jnp.stack([dense_init(k, shape, fan_in) for k in ks])

    layers = {
        "qkv_w": stack(lkeys[0], (D, qkv_cols), D),
        "attn_out_w": stack(lkeys[1], (cfg.num_heads * Dh, D),
                            cfg.num_heads * Dh),
        "mlp_in_w": stack(lkeys[2], (D, F), D),
        "mlp_out_w": stack(lkeys[3], (F, D), F),
        "ln1_scale": jnp.ones((L, D), dt),
        "ln2_scale": jnp.ones((L, D), dt),
    }
    if cfg.act == "swiglu":
        layers["mlp_gate_w"] = stack(lkeys[4], (D, F), D)
    if cfg.use_bias:
        layers.update({
            "ln1_bias": jnp.zeros((L, D), dt),
            "ln2_bias": jnp.zeros((L, D), dt),
            "qkv_b": jnp.zeros((L, qkv_cols), dt),
            "attn_out_b": jnp.zeros((L, D), dt),
            "mlp_in_b": jnp.zeros((L, F), dt),
            "mlp_out_b": jnp.zeros((L, D), dt),
        })
    out = {
        "embed": dense_init(k_emb, (cfg.vocab_size, D), D),
        "layers": layers,
        "ln_f_scale": jnp.ones((D,), dt),
    }
    if cfg.pos == "learned":
        out["pos_embed"] = (jax.random.normal(k_pos, (cfg.max_seq_len, D), dt)
                            * 0.02).astype(dt)
    if cfg.use_bias:
        out["ln_f_bias"] = jnp.zeros((D,), dt)
    return out


def param_specs(cfg: TransformerConfig, tp_axis: str = "tp",
                pp_axis: Optional[str] = None) -> PyTree:
    """PartitionSpec tree for Megatron-style TP (column/row split) with the
    stacked layer axis optionally sharded over the pipeline axis.

    Mirrors init_params' conditional keys (GQA/SwiGLU/no-bias/rope).  The
    GQA qkv layout ([q | k | v] flat columns) is a GSPMD hint, not a
    manual shard index — XLA reshards around the head split as needed.
    """
    pp = pp_axis  # leading stacked-layer dim
    layers = {
        "qkv_w": P(pp, None, tp_axis),
        "attn_out_w": P(pp, tp_axis, None),
        "mlp_in_w": P(pp, None, tp_axis),
        "mlp_out_w": P(pp, tp_axis, None),
        "ln1_scale": P(pp, None),
        "ln2_scale": P(pp, None),
    }
    if cfg.act == "swiglu":
        layers["mlp_gate_w"] = P(pp, None, tp_axis)
    if cfg.use_bias:
        layers.update({
            "ln1_bias": P(pp, None),
            "ln2_bias": P(pp, None),
            "qkv_b": P(pp, tp_axis),
            "attn_out_b": P(pp, None),
            "mlp_in_b": P(pp, tp_axis),
            "mlp_out_b": P(pp, None),
        })
    out = {
        "embed": P(None, None),
        "layers": layers,
        "ln_f_scale": P(None),
    }
    if cfg.pos == "learned":
        out["pos_embed"] = P(None, None)
    if cfg.use_bias:
        out["ln_f_bias"] = P(None)
    return out


# ---------------------------------------------------------------------------
# Forward pass.
# ---------------------------------------------------------------------------
def _layer_norm(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    y = y * scale.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def _rms_norm(x, scale, bias, eps=1e-6):
    """RMSNorm (no mean subtraction; llama-class blocks pass bias=None)."""
    x32 = x.astype(jnp.float32)
    y = x32 * jax.lax.rsqrt((x32 * x32).mean(-1, keepdims=True) + eps)
    y = y * scale.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


_NORMS = {"layernorm": _layer_norm, "rmsnorm": _rms_norm}


def _rope(x, theta: float):
    """Rotary position embedding on [B, H, S, Dh] (half-split layout).

    The rotation runs in float32: at positions near max_seq_len, bf16
    cos/sin (~3 significant digits) visibly degrade the rotation, so cast
    back to the compute dtype only after rotating (standard practice)."""
    B, H, S, Dh = x.shape
    half = Dh // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = jnp.arange(S, dtype=jnp.float32)[:, None] * freqs[None, :]
    cos = jnp.cos(angles)                   # [S, half], f32
    sin = jnp.sin(angles)
    x32 = x.astype(jnp.float32)
    x1, x2 = x32[..., :half], x32[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x2 * cos + x1 * sin], axis=-1).astype(x.dtype)


def dense_attention(q, k, v, causal: bool):
    """q,k,v: [B, H, S, Dh].  Softmax in f32 for stability."""
    dh = q.shape[-1]
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
    logits = logits / jnp.sqrt(jnp.asarray(dh, jnp.float32))
    if causal:
        s = q.shape[2]
        mask = jnp.tril(jnp.ones((s, s), bool))
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def flash_auto_block(S: int) -> int:
    """The flash adapter's auto block-size rule, exported so records (e.g.
    bench.py's JSON detail) can state the block that actually runs without
    duplicating the logic.  Returns 0 when no valid block exists (S not
    divisible by 64).

    S <= 512: the full sequence as one block (any multiple of 64 divides
    itself) — measured on a v5e chip at BERT-large geometry, S=512, batch
    48: block 512 = 33.7k tok/s vs 31.0k (256) vs 27.0k (128), i.e. the
    old fixed-128 choice left 25% on the table
    (bench_runs/r04_sweep1.jsonl); per-program VMEM stays small (block x
    block f32 logits at 512 is 1 MB).  S > 512: the largest of
    512/256/128/64 that divides S — the long-context regime was
    re-measured on-chip at llama_300m S=2048 batch 8 (causal, f32-tile
    kernel): block 512 = 27.0k tok/s vs 20.7k (256) vs 15.4k (128), so
    the old 128 tile left 75% on the table; the extra masked compute on
    causal diagonal blocks is far outweighed by fewer, fatter programs
    (bench_runs/r04_sweep5{,b}.jsonl).  Caveat: measured at S=2048 on
    the plain single-chip path; at gathered-sequence lengths (the
    strict ring/Ulysses path, S >= 8k) the 512 preference is an
    extrapolation — the relative diagonal waste only shrinks with S,
    but it is unmeasured there (S=8192 A/B queued in tools/mfu_sweep.py;
    attn_block=128 restores the old tile per-config if it regresses)."""
    if S <= 512:
        return S if S % 64 == 0 else 0
    for b in (512, 256, 128, 64):
        if S % b == 0:
            return b
    return 0


def flash_attention_fn(q, k, v, causal: bool, strict: bool = False,
                       block: int = 0, block_k: int = 0):
    """Adapter: [B, H, S, Dh] heads-layout -> the Pallas flash-attention
    kernel's [BH, S, Dh] layout, with automatic fallback to dense attention
    when the shape doesn't meet the kernel's tiling constraints (S must
    divide into 64- or 128-row blocks; Dh a multiple of 8).  strict=True
    raises instead of falling back — for callers where silent dense
    attention would materialize S x S logits at a length chosen precisely
    to avoid that (e.g. Ulysses long-context).

    block=0 auto-selects via `flash_auto_block` (full-sequence block at
    S <= 512, the largest of 512/256/128/64 dividing S beyond — both
    regimes measured on-chip; see its docstring for the evidence).  A nonzero
    override trades grid-iteration overhead against VMEM per program by
    hand (TransformerConfig.attn_block / BENCH_ATTN_BLOCK sweep it
    on-chip); `block_k` additionally decouples the K/V tile from the Q
    tile (TransformerConfig.attn_block_k) — at long S the Q tile sets
    program count while the K tile sets per-iteration VMEM and masked
    waste on causal diagonals, and the optimum need not be square.
    Overrides must divide S and be a multiple of 64 (the row-tile sizes
    the kernel guarantees); anything else reverts to the AUTO choice —
    never to dense, so a sweep value can't silently attribute dense
    throughput to a flash config."""
    B, H, S, Dh = q.shape
    if not block or S % block or block % 64:
        block = flash_auto_block(S)
    if not block_k or S % block_k or block_k % 64:
        block_k = block
    if block == 0 or Dh % 8:
        if strict:
            raise ValueError(
                f"flash attention needs seq_len divisible by 64 (got {S}) "
                f"and head_dim a multiple of 8 (got {Dh}); pad the "
                f"sequence or drop to attn='dense' explicitly")
        return dense_attention(q, k, v, causal)
    from ..ops.flash_attention import flash_attention

    def fold(t):
        return t.reshape(B * H, S, Dh)
    out = flash_attention(fold(q), fold(k), fold(v), causal, None,
                          block, block_k)
    return out.reshape(B, H, S, Dh)


_ATTN_IMPLS = {"dense": dense_attention, "flash": flash_attention_fn}


def _ckpt_name(x, name: str):
    """Tag an intermediate for name-based remat policies.

    A no-op unless the enclosing `jax.checkpoint` uses a name-aware policy
    (remat_policy="proj" below); then the tagged tensors are the ONLY ones
    saved and everything else is recomputed in backward.
    """
    from jax import ad_checkpoint
    return ad_checkpoint.checkpoint_name(x, name)


def _block(x, lp, cfg: TransformerConfig, attn_fn):
    """One transformer block.  x: [B, S, D]; lp: this layer's param slice."""
    dt = cfg.dtype
    B, S, D = x.shape
    H, Hkv, Dh = cfg.num_heads, cfg.kv_heads, cfg.head_dim
    norm = _NORMS[cfg.norm]

    def bias(name):
        return lp[name].astype(dt) if name in lp else None

    def add_bias(t, name):
        b = bias(name)
        return t if b is None else t + b

    h = norm(x, lp["ln1_scale"], bias("ln1_bias"))
    qkv = _ckpt_name(
        add_bias(jnp.einsum("bsd,de->bse", h, lp["qkv_w"].astype(dt)),
                 "qkv_b"), "qkv")
    q, k, v = jnp.split(qkv, [H * Dh, (H + Hkv) * Dh], axis=-1)

    def heads(t):
        return t.reshape(B, S, -1, Dh).transpose(0, 2, 1, 3)
    q, k, v = heads(q), heads(k), heads(v)
    if cfg.pos == "rope":
        q, k = _rope(q, cfg.rope_theta), _rope(k, cfg.rope_theta)
    if Hkv != H:
        # GQA: each query-head group shares one kv head — expand for the
        # attention kernel (the bandwidth saving is in params/KV-cache,
        # not this training-time broadcast).
        k = jnp.repeat(k, H // Hkv, axis=1)
        v = jnp.repeat(v, H // Hkv, axis=1)
    attn = attn_fn(q, k, v, cfg.causal)
    attn = _ckpt_name(attn.transpose(0, 2, 1, 3).reshape(B, S, -1),
                      "attn_ctx")
    attn = _ckpt_name(add_bias(
        jnp.einsum("bse,ed->bsd", attn, lp["attn_out_w"].astype(dt)),
        "attn_out_b"), "attn_proj")
    x = x + attn

    h = norm(x, lp["ln2_scale"], bias("ln2_bias"))
    up = add_bias(jnp.einsum("bsd,df->bsf", h, lp["mlp_in_w"].astype(dt)),
                  "mlp_in_b")
    if cfg.act == "swiglu":
        gate = jnp.einsum("bsd,df->bsf", h, lp["mlp_gate_w"].astype(dt))
        h = jax.nn.silu(gate) * up
    else:
        h = jax.nn.gelu(up)
    h = _ckpt_name(
        add_bias(jnp.einsum("bsf,fd->bsd", h, lp["mlp_out_w"].astype(dt)),
                 "mlp_out_b"), "ffn_out")
    return x + h


def forward_hidden(params: PyTree, tokens: jax.Array, cfg: TransformerConfig,
                   attn_fn=None) -> jax.Array:
    """tokens [B, S] int32 -> final hidden states [B, S, D] (post ln_f).

    Layers run under `lax.scan` over the stacked params; each step is
    optionally rematerialised.  `attn_fn(q,k,v,causal)` defaults to dense
    attention; ring attention (ops/ring_attention.py) slots in when the
    sequence is sharded over 'sp'.
    """
    if attn_fn is None:
        if cfg.attn_impl not in _ATTN_IMPLS:
            # "ring"/"ulysses" need a mesh-bound fn; anything else is a
            # typo — silently running dense would hide the config error
            # (and the S x S memory blow-up the user tried to avoid).
            raise ValueError(
                f"attn_impl={cfg.attn_impl!r} needs an explicit attn_fn "
                f"(ring/Ulysses: ops.ring_attention.make_ring_attn_fn / "
                f"make_ulysses_attn_fn); built-ins: "
                f"{sorted(_ATTN_IMPLS)}")
        attn_fn = _ATTN_IMPLS[cfg.attn_impl]
        if cfg.attn_impl == "flash" and (cfg.attn_block
                                         or cfg.attn_block_k):
            attn_fn = functools.partial(flash_attention_fn,
                                        block=cfg.attn_block,
                                        block_k=cfg.attn_block_k)
    dt = cfg.dtype
    B, S = tokens.shape
    x = params["embed"].astype(dt)[tokens]
    if cfg.pos == "learned":
        x = x + params["pos_embed"].astype(dt)[:S]

    def body(carry, lp):
        y = _block(carry, lp, cfg, attn_fn)
        return y, None

    if cfg.remat:
        policies = {
            "none": None,
            "dots": jax.checkpoint_policies.checkpoint_dots,
            "dots_no_batch":
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            # Selective "minimal" remat (the transformer sweet spot): save
            # only the model-dim projection outputs — qkv, attention
            # context/projection, ffn down — which are O(B·S·D), and
            # recompute the expensive-to-store pieces (S x S attention
            # logits/probs, the 4D-wide FFN up + activation, the f32 norm
            # intermediates) in backward.  vs full remat ("none") this
            # skips re-running ~2/3 of the matmul FLOPs; vs "dots" it
            # avoids saving the O(B·H·S²) and O(B·S·4D) tensors that blow
            # HBM at large batch.
            "proj": jax.checkpoint_policies.save_only_these_names(
                "qkv", "attn_ctx", "attn_proj", "ffn_out"),
        }
        if cfg.remat_policy not in policies:
            raise ValueError(f"remat_policy={cfg.remat_policy!r}; "
                             f"options: {sorted(policies)}")
        step = jax.checkpoint(body, policy=policies[cfg.remat_policy])
    else:
        step = body
    x, _ = lax.scan(step, x, params["layers"], unroll=cfg.scan_unroll)
    return _NORMS[cfg.norm](x, params["ln_f_scale"], params.get("ln_f_bias"))


def forward(params: PyTree, tokens: jax.Array, cfg: TransformerConfig,
            attn_fn=None) -> jax.Array:
    """tokens [B, S] int32 -> logits [B, S, vocab] (f32).

    Weight-tied readout against the embedding (keeps the big vocab matmul
    on the MXU once, not twice), computed in the activation dtype with f32
    accumulation — the MXU-native form; an all-f32 matmul would run in
    multi-pass emulation on TPU.
    """
    x = forward_hidden(params, tokens, cfg, attn_fn=attn_fn)
    return jnp.einsum("bsd,vd->bsv", x,
                      params["embed"].astype(x.dtype),
                      preferred_element_type=jnp.float32)


def fused_nll_sum(x: jax.Array, embed: jax.Array, targets: jax.Array,
                  chunk_rows: int) -> jax.Array:
    """Streamed weight-tied LM cross-entropy: SUM of per-row NLL without
    ever materializing the full [B*S, vocab] logits.  (Callers divide by
    their own token count — the hybrid shard_map step normalizes by the
    GLOBAL count across mesh axes.)

    Rows are processed in `chunk_rows`-sized chunks under `lax.scan`; each
    chunk computes its logits (activation-dtype matmul, f32 accumulation),
    reduces them to logsumexp + target logit, and is wrapped in
    `jax.checkpoint` so the backward pass recomputes the chunk logits
    instead of saving them.  Meant to run on per-shard (local) inputs —
    build_train_step's shard_map and the hybrid step both satisfy this; a
    GSPMD (jit-sharded) caller whose batch axis is sharded should expect
    the partitioner to move data across shards for the chunked scan.  Peak logits memory drops from O(B*S*V) to
    O(chunk_rows*V) in both passes; the matmul work is unchanged and stays
    MXU-shaped.  (Reference analog: BytePS's whole pitch is removing
    non-compute bottlenecks from the training step — docs/performance.md;
    here the bottleneck is HBM traffic rather than network.)
    """
    B, S, D = x.shape
    N = B * S
    C = min(chunk_rows, N)
    xs = x.reshape(N, D)
    ts = targets.reshape(N)
    pad = (-N) % C
    if pad:
        xs = jnp.concatenate([xs, jnp.zeros((pad, D), xs.dtype)])
        ts = jnp.concatenate([ts, jnp.zeros((pad,), ts.dtype)])
    w = jnp.concatenate([jnp.ones((N,), jnp.float32),
                         jnp.zeros((pad,), jnp.float32)])
    nc = (N + pad) // C
    emb = embed.astype(x.dtype)

    def chunk_nll_sum(xc, tc, wc):
        logits = jnp.einsum("cd,vd->cv", xc, emb,
                            preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, tc[:, None], axis=1)[:, 0]
        return ((lse - tgt) * wc).sum()

    chunk_nll_sum = jax.checkpoint(chunk_nll_sum)

    def body(acc, args):
        return acc + chunk_nll_sum(*args), None

    total, _ = lax.scan(body, jnp.zeros((), jnp.float32),
                        (xs.reshape(nc, C, D), ts.reshape(nc, C),
                         w.reshape(nc, C)))
    return total


def loss_fn(params: PyTree, batch: Tuple[jax.Array, jax.Array],
            cfg: TransformerConfig, attn_fn=None) -> jax.Array:
    """Cross-entropy LM loss.  batch = (tokens [B,S], targets [B,S]).

    With cfg.ce_chunk_rows > 0 the LM head is streamed (see _fused_lm_loss);
    otherwise the classic full-logits log_softmax path runs.  Both compute
    the same value up to f32 reduction order.
    """
    tokens, targets = batch
    if cfg.ce_chunk_rows:
        x = forward_hidden(params, tokens, cfg, attn_fn=attn_fn)
        return fused_nll_sum(x, params["embed"], targets,
                             cfg.ce_chunk_rows) / targets.size
    logits = forward(params, tokens, cfg, attn_fn=attn_fn)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()


def num_params(params: PyTree) -> int:
    return sum(p.size for p in jax.tree.leaves(params))


def flops_per_token(cfg: TransformerConfig) -> float:
    """Approximate training FLOPs/token (6N rule + attention)."""
    qkv_cols = (cfg.num_heads + 2 * cfg.kv_heads) * cfg.head_dim
    mlp_mats = 3 if cfg.act == "swiglu" else 2
    n = (cfg.num_layers * (cfg.d_model * qkv_cols                 # qkv
                           + cfg.num_heads * cfg.head_dim * cfg.d_model
                           + mlp_mats * cfg.d_model * cfg.d_ff)   # mlp
         + cfg.vocab_size * cfg.d_model)
    attn = cfg.num_layers * 2 * cfg.max_seq_len * cfg.d_model
    return 6.0 * (n + attn)


def synthetic_batch(rng: jax.Array, batch_size: int, seq_len: int,
                    cfg: TransformerConfig) -> Tuple[jax.Array, jax.Array]:
    """Random token batch for benchmarking (the reference benchmarks with
    synthetic data too — example/pytorch/benchmark_byteps.py)."""
    toks = jax.random.randint(rng, (batch_size, seq_len + 1), 0,
                              cfg.vocab_size, jnp.int32)
    return toks[:, :-1], toks[:, 1:]
