"""Model families for byteps_tpu.

The reference ships benchmark/example models via torchvision/gluon model
zoos (reference: example/pytorch/benchmark_byteps.py uses
torchvision.models, example/mxnet uses gluon model_zoo); this package is
the in-tree TPU-native equivalent: a transformer LM family (flagship —
BERT-large is the reference's headline benchmark, README.md:38-46), a CNN
family (ResNet/VGG — docs/performance.md benchmarks), and an MNIST MLP.
"""

from . import transformer
from . import cnn
from . import mlp

from .transformer import (
    TransformerConfig, get_config as get_transformer_config,
    init_params as init_transformer, forward as transformer_forward,
    loss_fn as transformer_loss,
)
from .cnn import create_cnn, cnn_loss_fn
from .mlp import (
    init_params as init_mlp, forward as mlp_forward, loss_fn as mlp_loss,
)

__all__ = [
    "transformer", "cnn", "mlp",
    "TransformerConfig", "get_transformer_config", "init_transformer",
    "transformer_forward", "transformer_loss",
    "create_cnn", "cnn_loss_fn",
    "init_mlp", "mlp_forward", "mlp_loss",
]
