"""Hybrid-parallel transformer: one train step over all five mesh axes.

The reference framework is DP-only (SURVEY §2.6); this module is the
"every axis at once" integration the TPU build adds on top: a transformer
LM (optionally Switch-MoE) whose single `shard_map` training step composes

  - dp × ep : batch sharding (expert ranks double as data ranks, the
              DeepSpeed-MoE convention),
  - sp      : sequence sharding with ring attention (ops/ring_attention),
  - tp      : Megatron column/row sharded projections (parallel/tensor_
              parallel — separate wq/wk/wv so head sharding stays clean),
  - pp      : SPMD GPipe over stacked layer slices (parallel/pipeline),
  - ep      : Switch-MoE expert dispatch (parallel/expert).

Gradient synchronization is explicit and per-parameter-group, the manual
analog of what GSPMD derives:

  group                         grads psummed over
  ------------------------------------------------
  non-stage (embed/pos/ln_f)    dp, ep, sp, pp   (loss masked to the last
                                                  pp rank so embed's head
                                                  path and input path sum
                                                  correctly — see _loss)
  stage, dense/tp               dp, ep, sp       (owned per pp rank)
  stage, expert (ffn_e_*)       dp, sp           (owned per (pp, ep) rank)

The Switch load-balancing aux loss is folded in whenever
`aux_loss_weight > 0`, including under pp: the aux scalar rides out-of-band
beside the pipeline's activation carry, accumulated per stage over its real
microbatch ticks (parallel/pipeline.gpipe_spmd with_aux=True).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..common.compat import axis_size as _axis_size
from ..common.compat import shard_map as _shard_map
from ..ops.ring_attention import ring_attention_shard
from ..parallel import pipeline as pp_mod
from ..parallel import tensor_parallel as tp_mod
from ..parallel.expert import moe_core

PyTree = Any


@dataclasses.dataclass(frozen=True)
class HybridConfig:
    vocab_size: int = 1024
    num_layers: int = 4
    d_model: int = 64
    num_heads: int = 4
    d_ff: int = 128
    max_seq_len: int = 128
    num_experts: int = 0          # 0 = dense MLP in every block
    capacity_factor: float = 2.0
    #: Switch load-balancing aux-loss weight (0 = off).  Note: the aux term
    #: is an expectation over the LOCAL token shard, so its value depends
    #: (mildly) on the sharding layout; enable it for real MoE training,
    #: leave 0 when bitwise cross-layout reproducibility matters.
    aux_loss_weight: float = 0.0
    dtype: Any = jnp.float32
    causal: bool = True
    #: > 0 streams the LM-head cross-entropy in row chunks of this size so
    #: the [B*S, vocab] logits are never materialized (see
    #: transformer.fused_nll_sum); 0 = full-logits path.
    ce_chunk_rows: int = 0

    @property
    def head_dim(self):
        return self.d_model // self.num_heads


def init_params(rng: jax.Array, cfg: HybridConfig) -> PyTree:
    L, D, F, E = cfg.num_layers, cfg.d_model, cfg.d_ff, cfg.num_experts
    ks = jax.random.split(rng, 12)

    def w(key, shape, fan_in):
        return jax.random.normal(key, shape, jnp.float32) / jnp.sqrt(fan_in)

    layers: Dict[str, jax.Array] = {
        "wq": w(ks[0], (L, D, D), D),
        "wk": w(ks[1], (L, D, D), D),
        "wv": w(ks[2], (L, D, D), D),
        "wo": w(ks[3], (L, D, D), D),
        "ln1_scale": jnp.ones((L, D)), "ln1_bias": jnp.zeros((L, D)),
        "ln2_scale": jnp.ones((L, D)), "ln2_bias": jnp.zeros((L, D)),
    }
    if E > 0:
        layers.update({
            "gate_w": w(ks[4], (L, D, E), D),
            "ffn_e_in": w(ks[5], (L, E, D, F), D),
            "ffn_e_out": w(ks[6], (L, E, F, D), F),
        })
    else:
        layers.update({
            "mlp_in": w(ks[7], (L, D, F), D),
            "mlp_out": w(ks[8], (L, F, D), F),
        })
    return {
        "embed": w(ks[9], (cfg.vocab_size, D), D),
        "pos": 0.02 * jax.random.normal(ks[10], (cfg.max_seq_len, D)),
        "ln_f_scale": jnp.ones((D,)),
        "ln_f_bias": jnp.zeros((D,)),
        "layers": layers,
    }


def param_specs(cfg: HybridConfig) -> PyTree:
    """Global PartitionSpecs; stacked layers carry the pp axis leading (after
    pipeline.shard_stage_params reshaping to [pp, L/pp, ...])."""
    layers = {
        "wq": P("pp", None, None, "tp"),
        "wk": P("pp", None, None, "tp"),
        "wv": P("pp", None, None, "tp"),
        "wo": P("pp", None, "tp", None),
        "ln1_scale": P("pp", None, None), "ln1_bias": P("pp", None, None),
        "ln2_scale": P("pp", None, None), "ln2_bias": P("pp", None, None),
    }
    if cfg.num_experts > 0:
        layers.update({
            "gate_w": P("pp", None, None, None),
            "ffn_e_in": P("pp", None, "ep", None, None),
            "ffn_e_out": P("pp", None, "ep", None, None),
        })
    else:
        layers.update({
            "mlp_in": P("pp", None, None, "tp"),
            "mlp_out": P("pp", None, "tp", None),
        })
    return {
        "embed": P(None, None),
        "pos": P(None, None),
        "ln_f_scale": P(None), "ln_f_bias": P(None),
        "layers": layers,
    }


def stage_params(params: PyTree, pp: int) -> PyTree:
    """[L, ...] stacked layers -> [pp, L/pp, ...] for the pp axis."""
    out = dict(params)
    out["layers"] = pp_mod.shard_stage_params(params["layers"], pp)
    return out


def _ln(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    return ((x32 - mu) * lax.rsqrt(var + eps) * scale + bias).astype(x.dtype)


def _block(lp, x, cfg: HybridConfig, f_tp, g_tp):
    """One hybrid block on a local activation x: [mb, s_local, D].
    Returns (x, aux) — aux is the MoE load-balancing loss (0 for dense)."""
    mb, s, D = x.shape
    dh = cfg.head_dim

    h = _ln(x, lp["ln1_scale"], lp["ln1_bias"])
    h = f_tp(h)                                   # Megatron f
    q = h @ lp["wq"]                              # [mb, s, D/tp]
    k = h @ lp["wk"]
    v = h @ lp["wv"]

    def heads(t):
        return t.reshape(mb, s, -1, dh).transpose(0, 2, 1, 3)
    attn = ring_attention_shard(heads(q), heads(k), heads(v),
                                causal=cfg.causal, axis_name="sp")
    attn = attn.transpose(0, 2, 1, 3).reshape(mb, s, -1)
    y = g_tp(attn @ lp["wo"])                    # Megatron g
    x = x + y

    h2 = _ln(x, lp["ln2_scale"], lp["ln2_bias"])
    if cfg.num_experts > 0:
        y2, aux = moe_core(lp["gate_w"], lp["ffn_e_in"], lp["ffn_e_out"],
                           h2.reshape(mb * s, D), cfg.capacity_factor, "ep")
        y2 = y2.reshape(mb, s, D)
    else:
        a = jax.nn.gelu(f_tp(h2) @ lp["mlp_in"])
        y2 = g_tp(a @ lp["mlp_out"])
        aux = jnp.zeros((), jnp.float32)
    return x + y2, aux


def _stage_fn(local_layers, x, cfg: HybridConfig, f_tp, g_tp):
    """Apply this pp rank's layer slice ([L/pp, ...] stacked) to x.
    Returns (out, aux_sum over this stage's layers)."""
    def body(carry, lp):
        h, aux = carry
        h, a = _block(lp, h, cfg, f_tp, g_tp)
        return (h, aux + a), None
    (out, aux), _ = lax.scan(
        body, (x, jnp.zeros((), jnp.float32)), local_layers)
    return out, aux


def build_hybrid_train_step(
    cfg: HybridConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    num_microbatches: int = 1,
    donate: bool = False,
    zero1: bool = False,
):
    """Returns (step, init_fn) where step(params, opt_state, (tokens,
    targets)) -> (params, opt_state, loss) is jitted over the full mesh and
    init_fn(rng) places params in their sharded layout.

    tokens/targets: [B, S] with B divisible by dp*ep*microbatches and S by
    sp.  params must come from init_fn (stacked layers pre-reshaped for pp).

    `zero1=True` additionally shards the optimizer state over 'dp'
    (ZeRO-1 on the explicit shard_map plane, the hand-built analog of
    parallel.sharded's GSPMD path): each param spec gains the dp axis on
    its first free dp-divisible dimension, the optimizer update runs on
    the local 1/dp shard of grads/params/state, and only the UPDATES are
    all-gathered back — Adam moments drop to 1/dp per device.  A
    replicated opt_state from `optimizer.init` is resharded on first
    call; at dp=1 the step is identical to zero1=False.
    """
    pp = int(mesh.shape.get("pp", 1))
    specs = param_specs(cfg)
    batch_spec = P(("dp", "ep"), "sp")

    f_tp = tp_mod.copy_to("tp")
    g_tp = tp_mod.reduce_from("tp")

    def loss_fn(params, tokens, targets):
        # [B_loc, S_loc] on this (dp,ep,sp) coordinate; replicated over tp
        # and pp.
        B, S = tokens.shape
        sp_idx = lax.axis_index("sp")
        x = params["embed"][tokens].astype(cfg.dtype)
        pos = lax.dynamic_slice_in_dim(params["pos"], sp_idx * S, S, 0)
        x = x + pos.astype(cfg.dtype)

        # Local stage slice: [pp, L/pp, ...] sharded over 'pp' arrives as
        # [1, L/pp, ...]; drop the leading singleton.
        local_layers = jax.tree.map(lambda l: l[0], params["layers"])
        run = functools.partial(_stage_fn, cfg=cfg, f_tp=f_tp, g_tp=g_tp)
        if pp > 1:
            # The aux scalar rides out-of-band beside the activation carry:
            # each pp rank accumulates its own stage's aux over its real
            # microbatch ticks (bubbles masked), so the router keeps its
            # load-balancing signal under pipeline parallelism.
            x, aux = pp_mod.gpipe_spmd(
                run, local_layers, x, num_microbatches, axis_name="pp",
                with_aux=True)
            # Per-microbatch aux terms are means over mb tokens; averaging
            # over M matches the single-pass (pp=1) per-token mean.
            aux = aux / num_microbatches
        else:
            x, aux = run(local_layers, x)

        x = _ln(x, params["ln_f_scale"], params["ln_f_bias"])
        if cfg.ce_chunk_rows:
            # Streamed LM head: per-chunk logits + logsumexp under
            # scan+checkpoint, never materializing [B*S, V] (same fused
            # path as the flagship model, transformer.fused_nll_sum).
            from .transformer import fused_nll_sum
            nll_sum = fused_nll_sum(x, params["embed"], targets,
                                    cfg.ce_chunk_rows)
        else:
            logits = jnp.einsum("bsd,vd->bsv", x.astype(jnp.float32),
                                params["embed"])
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]
            nll_sum = nll.sum()
        # Normalize by the GLOBAL token count; mask to the last pp stage so
        # psum over pp double-counts neither the head path nor the input
        # path of the shared embedding.
        denom = (B * _axis_size("dp") * _axis_size("ep")
                 * S * _axis_size("sp"))
        loss = nll_sum / denom
        # Mask the token loss to the last pp stage so psum over pp
        # double-counts neither the head path nor the input path of the
        # shared embedding.  The aux term stays UNmasked: each pp rank owns
        # the aux of its layer slice (distinct layers), so per-rank terms
        # sum to the whole-model aux under the final pp psum.
        loss = jnp.where(lax.axis_index("pp") == pp - 1, loss, 0.0)
        if cfg.num_experts > 0 and cfg.aux_loss_weight > 0.0:
            # Mean aux over layers and over the (dp, ep, sp) shards — the
            # final psum over those axes turns the per-shard term into the
            # cross-shard mean.
            shards = (_axis_size("dp") * _axis_size("ep")
                      * _axis_size("sp"))
            loss = loss + cfg.aux_loss_weight * aux / (
                cfg.num_layers * shards)
        return loss

    def make_grad_sync(dp_axes):
        """Cross-shard gradient reduction.  `dp_axes` is a params-shaped
        tree of ints: the dimension each leaf's 1/dp shard lives on, or
        -1 for leaves that stay whole (zero1 off, or no free divisible
        axis).  Whole leaves get the full psum; dp-sharded leaves psum
        only the non-dp axes and REDUCE-SCATTER over dp — each rank
        receives exactly the shard its optimizer update consumes, so the
        dp wire cost is scatter + (update) gather = one ring
        all-reduce, not all-reduce + gather."""
        def sync(path, g, ax):
            keys = [getattr(k, "key", getattr(k, "name", "")) for k in path]
            if "layers" in keys:
                if any(str(k).startswith("ffn_e") for k in keys):
                    nondp = ("sp",)
                else:
                    nondp = ("ep", "sp")
            else:
                nondp = ("ep", "sp", "pp")
            if ax < 0:
                return lax.psum(g, ("dp",) + nondp)
            g = lax.psum(g, nondp)
            return lax.psum_scatter(g, "dp", scatter_dimension=ax,
                                    tiled=True)
        return lambda grads: jax.tree_util.tree_map_with_path(
            sync, grads, dp_axes)

    def make_update_leg(dp_axes):
        """Optimizer leg: grads for dp-sharded leaves already arrive as
        this rank's shard (reduce-scattered by grad_sync); params are
        sliced locally (free — they are replicated over dp) and only the
        UPDATES are all-gathered back."""
        def slice_dp(x, ax):
            if ax < 0:
                return x
            n = _axis_size("dp")
            size = x.shape[ax] // n
            return lax.dynamic_slice_in_dim(
                x, lax.axis_index("dp") * size, size, ax)

        def gather_dp(u, ax):
            if ax < 0:
                return u
            return lax.all_gather(u, "dp", axis=ax, tiled=True)

        def update_leg(params, opt_state, grads):
            p_s = jax.tree.map(slice_dp, params, dp_axes)
            # State leaves arrive as their local shard (in_specs carry
            # the dp-upgraded layout); the update math runs on 1/dp of
            # every sharded leaf, so the moment buffers never exist
            # whole on any device.
            updates_s, opt_state = optimizer.update(grads, opt_state, p_s)
            updates = jax.tree.map(gather_dp, updates_s, dp_axes)
            return optax.apply_updates(params, updates), opt_state
        return update_leg

    def make_sm_step(grad_sync, update_leg):
        def _step(params, opt_state, batch):
            tokens, targets = batch
            loss, grads = jax.value_and_grad(
                lambda p: loss_fn(p, tokens, targets))(params)
            grads = grad_sync(grads)
            params, opt_state = update_leg(params, opt_state, grads)
            loss = lax.psum(loss, ("dp", "ep", "sp", "pp"))
            return params, opt_state, loss
        return _step

    # Optimizer-state specs: shape-match against params (adam mu/nu inherit
    # the param layout; scalars replicate).  With zero1 the param specs are
    # first upgraded with the dp axis, and the state follows THAT layout.
    # The shard_map+jit is built once per opt_state structure and cached
    # (rebuilding per call would retrace).
    def make_step():
        from ..parallel.sharded import (_is_spec, _shard_free_axis,
                                        opt_state_specs)
        cache = {}

        def dp_axis_of(old: P, new: P) -> int:
            for i, e in enumerate(new):
                if e == "dp" and (i >= len(old) or old[i] != "dp"):
                    return i
            return -1

        def call(params, opt_state, batch):
            key = jax.tree.structure(opt_state)
            if key not in cache:
                if zero1:
                    p_up = _shard_free_axis(specs, params, mesh, "dp",
                                            min_shard_elems=1024)
                else:
                    p_up = specs
                dp_axes = jax.tree.map(dp_axis_of, specs, p_up,
                                       is_leaf=_is_spec)
                o_specs = opt_state_specs(optimizer, params, p_up)
                sm = _shard_map(
                    make_sm_step(make_grad_sync(dp_axes),
                                 make_update_leg(dp_axes)), mesh=mesh,
                    in_specs=(specs, o_specs, (batch_spec, batch_spec)),
                    out_specs=(specs, o_specs, P()),
                    check_vma=False)
                donate_argnums = (0, 1) if donate else ()
                cache[key] = jax.jit(sm, donate_argnums=donate_argnums)
            return cache[key](params, opt_state, batch)
        return call

    def init_fn(rng):
        params = stage_params(init_params(rng, cfg), pp)
        from ..parallel.sharded import shard_params
        return shard_params(params, mesh, specs)

    return make_step(), init_fn
