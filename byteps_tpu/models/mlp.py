"""MNIST-scale MLP — the "hello world" model family.

Every reference framework plugin ships an MNIST example
(reference: example/pytorch/train_mnist_byteps.py, example/mxnet/
train_mnist_byteps.py, example/keras/mnist_advanced.py); this is the
pure-JAX equivalent used by the end-to-end training tests.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp

PyTree = Any


def init_params(rng: jax.Array, sizes: Sequence[int] = (784, 256, 128, 10),
                dtype=jnp.float32) -> PyTree:
    params = []
    keys = jax.random.split(rng, len(sizes) - 1)
    for k, (fin, fout) in zip(keys, zip(sizes[:-1], sizes[1:])):
        w = jax.random.normal(k, (fin, fout), dtype) / jnp.sqrt(fin)
        params.append({"w": w, "b": jnp.zeros((fout,), dtype)})
    return params


def forward(params: PyTree, x: jax.Array) -> jax.Array:
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jax.nn.relu(x)
    return x


def loss_fn(params: PyTree, batch: Tuple[jax.Array, jax.Array]) -> jax.Array:
    x, y = batch
    logits = forward(params, x)
    logp = jax.nn.log_softmax(logits)
    return -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()


def accuracy(params: PyTree, batch: Tuple[jax.Array, jax.Array]) -> jax.Array:
    x, y = batch
    return (forward(params, x).argmax(-1) == y).mean()
