"""CNN model family: ResNet and VGG (flax.linen).

The reference's throughput benchmarks are ResNet-50 and VGG-16
(reference: docs/performance.md:5-26, example/pytorch/benchmark_byteps.py
uses torchvision models).  These are the TPU-native counterparts: NHWC
layout (TPU conv-native), bf16 compute with f32 params/batch-stats, built
with flax.linen so they drop straight into the DistributedOptimizer path.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckResNetBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2),
                 padding=[(3, 3), (3, 3)], name="conv_init")(x.astype(self.dtype))
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i,
                                   conv=conv, norm=norm, act=nn.relu,
                                   strides=strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=ResNetBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=ResNetBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3],
                   block_cls=BottleneckResNetBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3],
                    block_cls=BottleneckResNetBlock)


class VGG(nn.Module):
    """VGG-16/19 (docs/performance.md benchmarks VGG-16)."""
    cfg: Sequence  # ints = conv filters, "M" = maxpool
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(v, (3, 3), padding=[(1, 1), (1, 1)],
                            dtype=self.dtype)(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(4096, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(4096, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


_VGG16_CFG = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
              512, 512, 512, "M", 512, 512, 512, "M"]
_VGG19_CFG = [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
              512, 512, 512, 512, "M", 512, 512, 512, 512, "M"]

VGG16 = partial(VGG, cfg=_VGG16_CFG)
VGG19 = partial(VGG, cfg=_VGG19_CFG)


_CNN_TABLE = {"resnet18": ResNet18, "resnet34": ResNet34,
              "resnet50": ResNet50, "resnet101": ResNet101,
              "vgg16": VGG16, "vgg19": VGG19}
CNN_NAMES = tuple(_CNN_TABLE)


def create_cnn(name: str, num_classes: int = 1000, **kw) -> nn.Module:
    if name not in _CNN_TABLE:
        raise ValueError(
            f"unknown cnn {name!r}; options: {sorted(_CNN_TABLE)}")
    return _CNN_TABLE[name](num_classes=num_classes, **kw)


def cnn_loss_fn(model: nn.Module):
    """Returns loss(variables, batch) for softmax-CE image classification.

    `variables` is the full flax variable dict ({'params': ..., and
    'batch_stats': ... when the model has BatchNorm}).  Inference-mode norm
    (train=False) keeps the loss a pure function of `variables`, which is what
    the DP train-step builder differentiates; models that need train-mode
    batch-stats updates thread the mutable collection explicitly in their
    training script (see example/jax/train_imagenet_resnet_byteps.py).
    """
    def loss(variables, batch):
        images, labels = batch
        logits = model.apply(variables, images, train=False)
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()
    return loss
