"""Pipeline parallelism: SPMD GPipe over the 'pp' mesh axis.

Absent from the reference (SURVEY §2.6 — DP only) but first-class here.
The schedule is GPipe with M microbatches over P stages: every device runs
the same `lax.scan` of M+P-1 ticks; at each tick a stage applies its layer
slice to the microbatch it holds, then passes the activation to the next
stage with `lax.ppermute` (one hop over ICI).  Autodiff of the scan +
ppermute yields the reverse pipeline for the backward pass automatically —
no hand-built 1F1B machinery, XLA overlaps the permute with compute.

Stage weights live in the leading (stacked-layer) axis sharded over 'pp',
so the memory per device is L/P layers — the standard reason to pipeline.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from ..common.compat import axis_size as _axis_size

PyTree = Any


def gpipe_spmd(
    stage_fn: Callable[[PyTree, jax.Array], Any],
    stage_params: PyTree,
    x: jax.Array,
    num_microbatches: int,
    axis_name: str = "pp",
    with_aux: bool = False,
):
    """Run `x` through P pipeline stages (call under shard_map).

    stage_fn(stage_params, mb) -> mb applies THIS device's layer slice
    (or -> (mb, aux_scalar) when with_aux=True).
    `stage_params` are the local (already pp-sharded) stage weights.
    x: [B, ...] microbatched along axis 0 into `num_microbatches` chunks
    (B % num_microbatches == 0).  Returns [B, ...] final-stage outputs,
    replicated to every rank; with_aux additionally returns THIS stage's
    aux scalar summed over its real microbatch ticks (bubble ticks carry
    garbage activations and are masked out).  The aux stays per-rank —
    each pp rank owns its layers' aux term, so its gradient flows only
    into that rank's stage params and, through the ppermute chain, back to
    stage 0's embedding feed; summing across ranks happens in the caller's
    final loss psum.
    """
    P = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = num_microbatches
    B = x.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    mbs = x.reshape(M, B // M, *x.shape[1:])
    mb_shape = mbs.shape[1:]

    perm_fwd = [(i, (i + 1) % P) for i in range(P)]

    def run_stage(inp):
        res = stage_fn(stage_params, inp)
        return res if with_aux else (res, jnp.zeros((), jnp.float32))

    def tick(carry, t):
        prev_out, outs, aux_acc = carry
        # What arrives from the previous stage this tick.
        recvd = lax.ppermute(prev_out, axis_name, perm_fwd)
        # Stage 0 feeds fresh microbatches while they last.
        feed = lax.dynamic_index_in_dim(mbs, jnp.minimum(t, M - 1), axis=0,
                                        keepdims=False)
        inp = jnp.where(idx == 0, feed.astype(recvd.dtype), recvd)
        out, aux = run_stage(inp)
        # Stage `idx` works on real microbatch m = t - idx at this tick;
        # other ticks are pipeline bubbles whose aux is garbage.
        valid = jnp.logical_and(t >= idx, t - idx < M)
        aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
        # The last stage finishes microbatch m = t - (P-1) at this tick.
        m = t - (P - 1)
        mc = jnp.clip(m, 0, M - 1)
        cur = lax.dynamic_index_in_dim(outs, mc, axis=0, keepdims=False)
        write = jnp.where(jnp.logical_and(m >= 0, idx == P - 1), out, cur)
        outs = lax.dynamic_update_index_in_dim(outs, write, mc, axis=0)
        return (out, outs, aux_acc), None

    # Probe stage_fn's output aval (it may change the activation dtype) to
    # type the scan carry.
    probe = jax.eval_shape(
        lambda p, a: stage_fn(p, a)[0] if with_aux else stage_fn(p, a),
        stage_params, jax.ShapeDtypeStruct(mb_shape, x.dtype))
    out0 = jnp.zeros(probe.shape, probe.dtype)
    outs0 = jnp.zeros((M,) + probe.shape, probe.dtype)
    aux0 = jnp.zeros((), jnp.float32)

    (_, outs, aux_sum), _ = lax.scan(tick, (out0, outs0, aux0),
                                     jnp.arange(M + P - 1))

    # Results live on the last stage; replicate them to all ranks (cheap
    # relative to the pipeline itself; lets the loss/psum run replicated).
    outs = lax.all_gather(outs, axis_name, axis=0, tiled=False)[P - 1]
    result = outs.reshape((B,) + probe.shape[1:])
    return (result, aux_sum) if with_aux else result


def shard_stage_params(params: PyTree, num_stages: int) -> PyTree:
    """Reshape stacked-layer params [L, ...] -> [P, L/P, ...] so the leading
    axis can be sharded over 'pp' (each stage holds L/P layers)."""
    def f(p):
        L = p.shape[0]
        if L % num_stages != 0:
            raise ValueError(f"{L} layers not divisible into "
                             f"{num_stages} stages")
        return p.reshape(num_stages, L // num_stages, *p.shape[1:])
    return jax.tree.map(f, params)
