"""Tensor parallelism: Megatron-style column/row sharded matmuls.

Absent from the reference (SURVEY §2.6) but first-class here.  Two usage
modes:

  1. GSPMD (preferred): annotate weights with the PartitionSpecs from
     `models.transformer.param_specs` and let XLA place the collectives —
     column-parallel layers need no forward comm, row-parallel layers get
     one psum, exactly the f/g operators of Megatron-LM.
  2. Explicit (shard_map): the helpers below spell the same math out for
     code running under `shard_map`, where GSPMD is bypassed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def col_parallel_dense(x: jax.Array, w_local: jax.Array,
                       b_local: jax.Array = None) -> jax.Array:
    """Column-parallel dense: inputs replicated, weight column-sharded.
    y_local = x @ W_local — no communication in forward; autodiff inserts
    the psum on dx (the Megatron "f" operator)."""
    y = x @ w_local
    if b_local is not None:
        y = y + b_local
    return y


def row_parallel_dense(x_local: jax.Array, w_local: jax.Array,
                       b: jax.Array = None,
                       axis_name: str = "tp") -> jax.Array:
    """Row-parallel dense: inputs sharded on the contracting dim, weight
    row-sharded; partial products are psummed (the Megatron "g" operator).
    Bias is added once, post-reduction."""
    y = lax.psum(x_local @ w_local, axis_name)
    if b is not None:
        y = y + b
    return y


def tp_split(x: jax.Array, axis: int, axis_name: str = "tp") -> jax.Array:
    """Slice the local chunk of a replicated array along `axis` (activation
    entering a row-parallel layer)."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    size = x.shape[axis] // n
    return lax.dynamic_slice_in_dim(x, idx * size, size, axis)


def tp_all_gather(x_local: jax.Array, axis: int,
                  axis_name: str = "tp") -> jax.Array:
    """Re-assemble a sharded activation (exit of a column-parallel layer
    when the next op needs the full feature dim)."""
    return lax.all_gather(x_local, axis_name, axis=axis, tiled=True)
