"""Tensor parallelism: Megatron-style column/row sharded matmuls.

Absent from the reference (SURVEY §2.6) but first-class here.  Two usage
modes:

  1. GSPMD (preferred): annotate weights with the PartitionSpecs from
     `models.transformer.param_specs` and let XLA place the collectives —
     column-parallel layers need no forward comm, row-parallel layers get
     one psum, exactly the f/g operators of Megatron-LM.
  2. Explicit (shard_map): the helpers below spell the same math out for
     code running under `shard_map`, where GSPMD is bypassed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..common.compat import axis_size as _axis_size


def col_parallel_dense(x: jax.Array, w_local: jax.Array,
                       b_local: jax.Array = None) -> jax.Array:
    """Column-parallel dense: inputs replicated, weight column-sharded.
    y_local = x @ W_local — no communication in forward; autodiff inserts
    the psum on dx (the Megatron "f" operator)."""
    y = x @ w_local
    if b_local is not None:
        y = y + b_local
    return y


def row_parallel_dense(x_local: jax.Array, w_local: jax.Array,
                       b: jax.Array = None,
                       axis_name: str = "tp") -> jax.Array:
    """Row-parallel dense: inputs sharded on the contracting dim, weight
    row-sharded; partial products are psummed (the Megatron "g" operator,
    with the transpose-safe custom vjp).  Bias is added once,
    post-reduction."""
    y = reduce_from(axis_name)(x_local @ w_local)
    if b is not None:
        y = y + b
    return y


def copy_to(axis_name: str):
    """The Megatron "f" operator: forward identity, backward all-reduce.

    Under shard_map autodiff is purely local, so a replicated activation
    entering column-parallel branches needs its cotangents summed across the
    tp ranks explicitly; this factory returns that identity-with-psum-vjp.
    """
    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, g):
        return (lax.psum(g, axis_name),)

    f.defvjp(fwd, bwd)
    return f


def reduce_from(axis_name: str):
    """The Megatron "g" operator: forward all-reduce, backward identity.

    Raw `lax.psum` must NOT be differentiated through under
    shard_map(check_vma=False): its transpose is another psum, which
    over-counts the cotangent by the axis size when the downstream loss is
    computed replicated on every rank.  This custom-vjp pins the correct
    adjoint (the replicated cotangent passes through once).
    """
    @jax.custom_vjp
    def g(x):
        return lax.psum(x, axis_name)

    def fwd(x):
        return lax.psum(x, axis_name), None

    def bwd(_, ct):
        return (ct,)

    g.defvjp(fwd, bwd)
    return g


def tp_split(x: jax.Array, axis: int, axis_name: str = "tp") -> jax.Array:
    """Slice the local chunk of a replicated array along `axis` (activation
    entering a row-parallel layer)."""
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    size = x.shape[axis] // n
    return lax.dynamic_slice_in_dim(x, idx * size, size, axis)


def tp_all_gather(x_local: jax.Array, axis: int,
                  axis_name: str = "tp") -> jax.Array:
    """Re-assemble a sharded activation (exit of a column-parallel layer
    when the next op needs the full feature dim)."""
    return lax.all_gather(x_local, axis_name, axis=axis, tiled=True)
