"""Expert parallelism: Switch-style MoE with all-to-all dispatch over 'ep'.

Absent from the reference (SURVEY §2.6) but first-class here.  Top-1
(Switch) routing with capacity limiting; experts are sharded over the 'ep'
mesh axis and tokens travel to their expert's device through one
`lax.all_to_all` each way — the TPU-idiomatic expert dispatch (the
all-to-all rides ICI; dispatch/combine are one-hot einsums that the MXU
chews through).
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..common.compat import axis_size as _axis_size
from ..common.compat import shard_map as _shard_map

PyTree = Any


def init_moe_params(rng: jax.Array, num_experts: int, d_model: int,
                    d_ff: int, dtype=jnp.float32) -> PyTree:
    kg, k1, k2 = jax.random.split(rng, 3)
    return {
        "gate_w": jax.random.normal(kg, (d_model, num_experts), dtype)
        / jnp.sqrt(d_model),
        "ffn_in": jax.random.normal(k1, (num_experts, d_model, d_ff), dtype)
        / jnp.sqrt(d_model),
        "ffn_out": jax.random.normal(k2, (num_experts, d_ff, d_model), dtype)
        / jnp.sqrt(d_ff),
    }


def moe_param_specs(ep_axis: str = "ep") -> PyTree:
    from jax.sharding import PartitionSpec as P
    return {"gate_w": P(None, None),
            "ffn_in": P(ep_axis, None, None),
            "ffn_out": P(ep_axis, None, None)}


def _dispatch_masks(gate_logits: jax.Array, num_experts: int, capacity: int):
    """Top-1 routing -> (dispatch [T,E,C] bool-ish, combine [T,E,C] f32,
    aux_loss).  T = local token count."""
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)                       # [T]
    gate = jnp.take_along_axis(probs, expert[:, None], -1)[:, 0]
    onehot = jax.nn.one_hot(expert, num_experts, dtype=jnp.float32)  # [T,E]
    # Position of each token within its expert's queue.
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot         # [T,E]
    keep = pos < capacity
    onehot = onehot * keep
    pos_idx = pos.astype(jnp.int32)
    cap_onehot = jax.nn.one_hot(pos_idx, capacity, dtype=jnp.float32)
    dispatch = onehot[..., None] * cap_onehot                 # [T,E,C]
    combine = dispatch * gate[:, None, None]
    # Switch load-balancing auxiliary loss.
    density = onehot.mean(axis=0)
    density_proxy = probs.mean(axis=0)
    aux = (density * density_proxy).sum() * num_experts
    return dispatch, combine, aux


def moe_core(gate_w: jax.Array, ffn_in: jax.Array, ffn_out: jax.Array,
             x: jax.Array, capacity_factor: float = 2.0,
             axis_name: str = "ep") -> Tuple[jax.Array, jax.Array]:
    """The Switch-MoE data path on local tokens (call under shard_map).

    x: [T_local, D]; ffn_in/ffn_out: this rank's expert slice
    [E_local, D, F] / [E_local, F, D]; gate_w [D, E_global] replicated.
    Returns (y [T_local, D], aux load-balancing loss — local, not reduced).
    Shared by the standalone moe_layer and the hybrid model's FFN so the
    dispatch/capacity logic exists exactly once.
    """
    world = _axis_size(axis_name)
    e_local = ffn_in.shape[0]
    E = e_local * world
    T = x.shape[0]
    capacity = max(1, int(capacity_factor * T / E))

    logits = x @ gate_w                                        # [T, E]
    dispatch, combine, aux = _dispatch_masks(logits, E, capacity)

    # Tokens -> expert buffers [E, C, D]; split experts across ranks, gather
    # the share of every peer's tokens for my local experts.
    buffers = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    # [E, C, D] -> [E/world, world*C, D]
    recv = lax.all_to_all(buffers, axis_name, split_axis=0, concat_axis=1,
                          tiled=True)
    h = jnp.einsum("ecd,edf->ecf", recv, ffn_in.astype(jnp.float32))
    h = jax.nn.gelu(h)
    h = jnp.einsum("ecf,efd->ecd", h, ffn_out.astype(jnp.float32))
    # Route results back to the owners of the tokens.
    back = lax.all_to_all(h, axis_name, split_axis=1, concat_axis=0,
                          tiled=True)                          # [E, C, D]
    y = jnp.einsum("tec,ecd->td", combine, back)
    return y.astype(x.dtype), aux


def moe_layer_shard(params: PyTree, x: jax.Array, capacity_factor: float = 2.0,
                    axis_name: str = "ep") -> Tuple[jax.Array, jax.Array]:
    """Per-shard Switch-MoE layer (call under shard_map).

    x: [T_local, D] tokens on this device; params['ffn_*'] hold the LOCAL
    expert slice [E_local, ...]; gate_w is replicated.  Returns (y, aux_loss).
    """
    y, aux = moe_core(params["gate_w"], params["ffn_in"], params["ffn_out"],
                      x, capacity_factor, axis_name)
    return y, lax.pmean(aux, axis_name)


def moe_layer(params: PyTree, x: jax.Array, mesh, capacity_factor: float = 2.0,
              axis_name: str = "ep") -> Tuple[jax.Array, jax.Array]:
    """Full-shape MoE layer: shard tokens over `axis_name`, experts likewise.

    x: [T, D] (T divisible by the ep axis size).  Wraps moe_layer_shard in
    shard_map for use inside an outer jit.
    """
    from jax.sharding import PartitionSpec as P
    specs = moe_param_specs(axis_name)

    f = functools.partial(moe_layer_shard, capacity_factor=capacity_factor,
                          axis_name=axis_name)
    return _shard_map(
        f, mesh=mesh,
        in_specs=(specs, P(axis_name, None)),
        out_specs=(P(axis_name, None), P()),
        check_vma=False)(params, x)
