"""Row-sparse embedding tables: the PS tier as a lookup tier.

The dense planes move whole tensors, so an embedding table pays wire
bytes proportional to its FULL size even when a step touches 0.1% of its
rows — the canonical recsys shape (millions of rows, read-dominated
pull traffic against sharded state, PAPER.md §1) is exactly what a
parameter server exists for.  This module is the worker-facing face of
the row-sparse plane (docs/sparse-embedding.md):

- the table lives SERVER-side, sharded row-wise across the PS tier
  (``shard = row % shards`` — consecutive hot rows spread instead of
  clustering on one server), larger than any worker's memory,
- ``push_pull`` ships ``(indices, rows)`` pairs both ways: wire bytes
  are proportional to touched rows, never to table size, and the
  server's row-wise CMD_OPT steps exactly the pushed rows (Adagrad/Adam
  slots materialize row-by-row server-side — dense optimizer state
  never exists on any worker),
- ``lookup`` is the read path: batched row pulls against the last
  published table state, served through the session's
  param_version-keyed hot-row LRU cache, so unchanged hot rows cost
  ZERO wire frames — and it works from pull-only "inference" sessions
  that are not round members and can never stall training.

Every shard is one wire key, so the ring places, drains, and migrates
embedding shards with the same laws as any other key (the embed
trailer on CMD_MIGRATE carries merge state, published rows, and
per-row step counts byte-equal).
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from ..core.native import get_core


class EmbeddingTable:
    """A server-resident ``rows x width`` f32 embedding table.

    Usage::

        table = EmbeddingTable(session, rows=10_000_000, width=64,
                               name="user_emb",
                               opt_kwargs={"opt": "adagrad", "lr": 0.01},
                               init=init_fn)
        for batch in data:
            emb = table.lookup(batch.ids)           # batched, cached
            grads = grad_fn(emb, batch)
            table.push_pull(batch.ids, grads)       # sparse round step

    ``opt_kwargs`` arms the row-wise server-resident optimizer (same
    surface as :class:`~byteps_tpu.parallel.server_opt.ServerOptTrainer`:
    ``{"opt": "adagrad", "lr": ...}`` / adam / momentum / sgd);
    ``init`` seeds the initial rows — either a full ``(rows, width)``
    array or a callable ``init(shard_rows, width, shard_idx)`` so a
    10M-row table never materializes whole on the worker.  Without
    ``opt_kwargs`` the table publishes per-round gradient SUMS (the
    dense unarmed semantics) — useful for tests, not for serving.

    A pull-only session builds the same table (same name / shards /
    shape — declaration is idempotent) and uses ``lookup`` only.
    """

    def __init__(self, session, rows: int, width: int,
                 name: str = "embedding",
                 shards: Optional[int] = None,
                 opt_kwargs: Optional[dict] = None,
                 init: Any = None):
        if rows <= 0 or width <= 0:
            raise ValueError(f"embedding shape must be positive, got "
                             f"{rows}x{width}")
        self._session = session
        self.rows, self.width = int(rows), int(width)
        self.name = name
        nsrv = max(1, len(getattr(session, "conns", [])) or 1)
        self.shards = max(1, min(int(shards) if shards else nsrv,
                                 self.rows))
        core = get_core()
        self._keys: List[int] = []
        self._shard_rows: List[int] = []
        for s in range(self.shards):
            key = core.declare_tensor(f"Embed.{name}.{s}")
            # Shard s holds global rows {r : r % shards == s} at local
            # index r // shards: ceil((rows - s) / shards) of them.
            srows = (self.rows - s + self.shards - 1) // self.shards
            session.declare_embedding(key, srows, self.width)
            self._keys.append(key)
            self._shard_rows.append(srows)
        if opt_kwargs:
            if getattr(session, "pull_only", False):
                raise RuntimeError(
                    "a pull-only session cannot arm the optimizer "
                    "(it is a reader); arm from a trainer session")
            for s, key in enumerate(self._keys):
                seed = self._shard_init(init, s)
                session.arm_embedding(key, dict(opt_kwargs), table=seed)

    def _shard_init(self, init: Any, s: int) -> Optional[np.ndarray]:
        if init is None:
            return None
        if callable(init):
            t = np.asarray(init(self._shard_rows[s], self.width, s),
                           dtype=np.float32)
        else:
            full = np.asarray(init, dtype=np.float32)
            if full.shape != (self.rows, self.width):
                raise ValueError(f"init shape {full.shape} != "
                                 f"{(self.rows, self.width)}")
            t = full[s::self.shards]
        if t.shape != (self._shard_rows[s], self.width):
            raise ValueError(f"shard {s} init shape {t.shape} != "
                             f"{(self._shard_rows[s], self.width)}")
        return t

    def _split(self, indices):
        idx = np.ascontiguousarray(np.asarray(indices).ravel(),
                                   dtype=np.int64)
        if idx.size and (idx.min() < 0 or idx.max() >= self.rows):
            raise IndexError(f"row index out of range for {self.rows}"
                             f"-row table")
        shard = idx % self.shards
        local = (idx // self.shards).astype(np.uint32)
        return idx, shard, local

    def push_pull(self, indices, grads) -> np.ndarray:
        """One sparse training step: merge this worker's ``(indices,
        grads)`` into the open round of EVERY shard (an untouched shard
        receives an EMPTY sparse push — presence without rows — so
        round completion never waits on a shard this batch missed),
        wait for the publishes, and return the post-publish rows for
        ``indices`` in caller order (post-optimizer parameters when
        armed, per-round sums otherwise).  Duplicate indices accumulate
        on the push and receive identical rows on the pull."""
        idx, shard, local = self._split(indices)
        g = np.ascontiguousarray(np.asarray(grads, dtype=np.float32))
        g = g.reshape(idx.size, self.width)
        out = np.empty((idx.size, self.width), dtype=np.float32)
        for s, key in enumerate(self._keys):
            mask = shard == s
            got = self._session.push_pull_sparse(key, local[mask],
                                                 g[mask])
            out[mask] = got
        return out

    def lookup(self, indices) -> np.ndarray:
        """Batched row read against the last PUBLISHED table state (the
        recsys serving path): ungated on the wire, cached hot rows cost
        zero frames, and shards no requested row lands on are not
        contacted at all.  Works from pull-only sessions."""
        idx, shard, local = self._split(indices)
        out = np.empty((idx.size, self.width), dtype=np.float32)
        for s, key in enumerate(self._keys):
            mask = shard == s
            if not mask.any():
                continue
            out[mask] = self._session.pull_rows(key, local[mask])
        return out

    @property
    def keys(self) -> List[int]:
        """Declared key per shard (for stats/doctor cross-reference)."""
        return list(self._keys)

    @property
    def table_bytes(self) -> int:
        """Declared f32 bytes resident across the PS tier."""
        return self.rows * self.width * 4

    def versions(self) -> List[Optional[int]]:
        """Last observed param_version per shard (None = never read).
        Monotone non-decreasing per shard — what pull-only readers
        assert across a ring drain."""
        return [self._session.embed_version(k) for k in self._keys]
