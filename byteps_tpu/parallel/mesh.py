"""Device mesh construction for the framework.

The reference's device topology is (machines × GPUs-per-machine) with NCCL
rings intra-node and ps-lite across nodes (reference: docs/architecture.md,
byteps/common/nccl_manager.cc).  The TPU-native equivalent is a single
`jax.sharding.Mesh` whose axes name the parallelism dimensions:

  - ``dp``  data parallelism (the reference's only strategy)
  - ``ici_dp`` / ``dcn_dp``  hierarchical split of dp into intra-slice (ICI)
    and inter-slice (DCN) axes, mirroring the reference's local-NCCL-reduce →
    ps-lite-push two-level reduction (reference: core_loops.cc:188-267 +
    536-616)
  - ``tp`` tensor parallelism, ``sp`` sequence/context parallelism,
    ``pp`` pipeline parallelism, ``ep`` expert parallelism — absent from the
    reference (SURVEY §2.6) but first-class here.

All collectives in byteps_tpu.ops ride these axis names; XLA lays ICI
collectives onto the torus automatically when the mesh is built with
`jax.experimental.mesh_utils.create_device_mesh`.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from ..common.config import get_config

# Canonical axis order: dcn-crossing axis outermost, then pp, dp, ep, sp, tp
# innermost (tp needs the fastest wires; dp tolerates DCN).
AXIS_ORDER = ("pp", "dp", "ep", "sp", "tp")

_mesh: Optional[Mesh] = None


def make_mesh(dp: int = 0, tp: int = 1, sp: int = 1, pp: int = 1, ep: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a mesh over `devices` (default: all). dp=0 means "the rest"."""
    devs = list(devices) if devices is not None else jax.devices()
    n = len(devs)
    other = tp * sp * pp * ep
    if dp <= 0:
        if n % other != 0:
            raise ValueError(
                f"device count {n} not divisible by tp*sp*pp*ep={other}")
        dp = n // other
    total = dp * other
    if total != n:
        raise ValueError(f"mesh {dp=}*{tp=}*{sp=}*{pp=}*{ep=}={total} != "
                         f"device count {n}")
    sizes = dict(pp=pp, dp=dp, ep=ep, sp=sp, tp=tp)
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    try:
        from jax.experimental import mesh_utils
        mesh_devs = mesh_utils.create_device_mesh(shape, devices=devs)
    except Exception:
        mesh_devs = np.asarray(devs).reshape(shape)
    return Mesh(mesh_devs, AXIS_ORDER)


def make_hierarchical_mesh(ici_size: Optional[int] = None,
                           devices: Optional[Sequence] = None) -> Mesh:
    """Two-level DP mesh ('dcn_dp', 'ici_dp') for hierarchical reduction.

    `ici_size` devices per ICI island; islands are connected over DCN.  The
    reference analog: GPUs under one PCIe switch reduce via NCCL, roots push
    over the network (reference: docs/architecture.md:26-33).
    None reads BYTEPS_TPU_ICI_SIZE (0 = all devices local, one island).
    """
    if ici_size is None:
        from ..common.config import get_config
        ici_size = get_config().ici_size
    devs = list(devices) if devices is not None else jax.devices()
    n = len(devs)
    if ici_size <= 0:
        ici_size = n
    if n % ici_size != 0:
        raise ValueError(f"{n} devices not divisible by ici_size={ici_size}")
    arr = np.asarray(devs).reshape(n // ici_size, ici_size)
    return Mesh(arr, ("dcn_dp", "ici_dp"))


def make_slice_mesh(num_members: int,
                    devices: Optional[Sequence] = None) -> Optional[Mesh]:
    """One-axis ``('ici_dp',)`` mesh for a slice's in-graph reduction
    (parallel/hierarchy.py): one device per slice member, so the
    intra-slice ``psum`` under ``shard_map`` runs on real device lanes.

    Returns None when the process has fewer addressable devices than
    members — the caller then falls back to a host-side sum (same
    values, different engine).  The device list is stable (jax.devices()
    order), so every member of a colocated slice builds the same mesh.
    """
    import jax

    n = max(1, int(num_members))
    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) < n:
        return None
    return Mesh(np.asarray(devs[:n]), ("ici_dp",))


def get_mesh(refresh: bool = False) -> Mesh:
    """Process-wide default mesh built from config (BYTEPS_TPU_MESH_*)."""
    global _mesh
    if _mesh is None or refresh:
        cfg = get_config(refresh=refresh)
        _mesh = make_mesh(dp=cfg.mesh_dp, tp=cfg.mesh_tp, sp=cfg.mesh_sp,
                          pp=cfg.mesh_pp, ep=cfg.mesh_ep)
    return _mesh


def set_mesh(mesh: Mesh) -> None:
    global _mesh
    _mesh = mesh


def reset_mesh() -> None:
    global _mesh
    _mesh = None


def dp_axis_size(mesh: Optional[Mesh] = None) -> int:
    m = mesh or get_mesh()
    return int(math.prod(m.shape[a] for a in ("dp",) if a in m.shape))
