"""Server-resident optimizer training: push gradients, pull *parameters*.

The sum-only PS contract (BytePS §1C) makes every worker pull the full
gradient sum and run the full optimizer redundantly N times, holding N
copies of optimizer state.  This trainer flips the key's publish stage
into parameter mode (CMD_OPT, arXiv 2004.13336 "Automatic Cross-Replica
Sharding of Weight Update"): each partition's ring owner runs the
optimizer step ONCE on the merged sum and publishes the post-update
parameters — workers push gradients exactly as before (codec/EF law
untouched) and adopt pulled parameters instead of sums, skipping the
local optax step entirely.  Partitions spread across the PS ring, so the
weight update is sharded server-by-server for free — the ZeRO-flavored
placement the ROADMAP names.

Two modes, one trainer:

- ``mode="server"`` — the new plane.  ``arm_server_opt`` declares the
  epoch-versioned optimizer config and seeds the initial params; every
  ``step(grads)`` is one push_pull whose pull IS the updated params.
  Per-worker optimizer-state bytes: ~0 (the slots live in the server's
  ``KeyState``; ``bps.get_server_stats()["opt_slot_bytes"]`` is where
  they went).
- ``mode="local"`` — the worker-local optax baseline: pull the sum, run
  the IDENTICAL optax optimizer here.  This is the reference trajectory
  the equivalence law pins: with fixed membership the two modes match
  f32-exactly, round by round, including under compression with EF
  (tests/test_server_opt.py; run the baseline under
  ``jax.disable_jit()`` for the bitwise comparison — eager optax and the
  server's update stage share every f32 op, while jitted XLA's traced
  ``pow`` in Adam's bias correction may differ by ~1 ULP).

The default mode comes from ``BYTEPS_TPU_SERVER_OPT`` (1 = server,
otherwise local), so a launch config can flip a job without touching
trainer code.

Failover: drain and scale-up migrate the optimizer slots byte-equal
(CMD_MIGRATE trailer).  After a SIGKILL failover hands a key range to a
fresh owner, the session re-declares the config and re-seeds params from
this trainer's adopted view (``params_fn``): stateless SGD recovers
bit-identically; momentum/Adam slots cannot be rebuilt from workers and
restart zeroed — see docs/server-optimizer.md "Failover".
"""

from __future__ import annotations

import os
from typing import Any, Optional

import numpy as np

PyTree = Any

#: optimizer-name -> required hyperparams (filled with optax defaults so
#: the canonical kwargs string the server parses is always explicit).
_DEFAULTS = {
    "sgd": {"lr": 0.01},
    "momentum": {"lr": 0.01, "mu": 0.9},
    "adam": {"lr": 0.001, "b1": 0.9, "b2": 0.999, "eps": 1e-8},
}


def _canonical_opt_kwargs(opt_kwargs: dict, grad_scale: float) -> dict:
    kw = {str(k): v for k, v in dict(opt_kwargs).items()}
    name = str(kw.pop("opt", "sgd"))
    if name not in _DEFAULTS:
        raise ValueError(
            f"server-resident optimizer {name!r} not supported "
            f"(have: {sorted(_DEFAULTS)})")
    full = dict(_DEFAULTS[name])
    for k, v in kw.items():
        if k not in full:
            raise ValueError(
                f"unknown hyperparam {k!r} for server optimizer "
                f"{name!r} (have: {sorted(full)})")
        full[k] = float(v)
    full = {k: float(v) for k, v in full.items()}
    full["opt"] = name
    if float(grad_scale) != 1.0:
        full["gscale"] = float(grad_scale)
    return full


class ServerOptTrainer:
    """Sync training whose optimizer step runs on the PS tier.

    Usage::

        trainer = ServerOptTrainer(session, params,
                                   {"opt": "adam", "lr": 1e-3},
                                   name="model", grad_scale=1.0 / N)
        for batch in data:
            grads = grad_fn(trainer.params, batch)
            trainer.step(grads)      # push grads, adopt updated params

    ``grad_scale`` is the factor applied to the merged gradient SUM
    before the optimizer consumes it (1/N for data-parallel averaging;
    default 1.0 = raw-sum semantics).  Applied identically in both
    modes, so local-vs-server trajectories stay comparable.
    """

    def __init__(self, session, params: PyTree, opt_kwargs: dict,
                 name: str = "serveropt",
                 declared_key: Optional[int] = None,
                 mode: Optional[str] = None,
                 grad_scale: float = 1.0,
                 hierarchy=None):
        import jax

        if getattr(session, "server_async", False):
            raise RuntimeError(
                "ServerOptTrainer needs sync rounds; against an async "
                "server there is no merge boundary for the update stage "
                "(use AsyncPSTrainer there)")
        if mode is None:
            mode = ("server"
                    if os.environ.get("BYTEPS_TPU_SERVER_OPT", "0") == "1"
                    else "local")
        if mode not in ("server", "local"):
            raise ValueError(f"mode must be 'server' or 'local', "
                             f"got {mode!r}")
        self._session = session
        self.mode = mode
        # Hierarchical reduction (BYTEPS_TPU_HIERARCHY=1): gradients
        # slice-reduce in-graph, the slice leader pushes the slice sum,
        # and the pulled value — post-update PARAMETERS in server mode —
        # broadcasts back to the slice.  grad_scale semantics are
        # untouched: the server scales the total sum (sum of slice
        # sums == sum over every chip).
        if hierarchy is None:
            from .hierarchy import maybe_reducer
            hierarchy = maybe_reducer(session)
        self._hier = hierarchy
        self._grad_scale = float(grad_scale)
        self._kw = _canonical_opt_kwargs(opt_kwargs, grad_scale)
        self._treedef = jax.tree.structure(params)
        leaves = jax.tree.leaves(params)
        self._shapes = [np.shape(l) for l in leaves]
        self._sizes = [int(np.size(l)) for l in leaves]
        self._dtypes = [np.asarray(l).dtype for l in leaves]
        if declared_key is None:
            from ..core.native import get_core
            declared_key = get_core().declare_tensor(f"ServerOpt.{name}")
        self._key = declared_key
        self._flat = self._flatten(params)
        self._rounds = 0
        if mode == "server":
            # Declare + seed; params_fn hands the session our CURRENT
            # adopted view as the failover re-seed source.  Always
            # effective from round 0: the trainer arms BEFORE its first
            # push, so every pull it ever adopts is parameters — a later
            # effective round would hand back pre-switch gradient SUMS
            # that step() would silently adopt as weights (deferred
            # switches belong to session-level propose_opt, where the
            # caller owns the pull interpretation).
            self._opt_state = None
            session.arm_server_opt(
                declared_key, self._flat, self._kw,
                params_fn=lambda: self._flat,
                effective_round=0)
        else:
            # Worker-local optax baseline — the trajectory the server
            # mode must match f32-exactly.
            self._opt = self._build_optax()
            import jax.numpy as jnp
            self._opt_state = self._opt.init(jnp.asarray(self._flat))

    def _build_optax(self):
        import optax
        kw = self._kw
        name = kw["opt"]
        if name == "sgd":
            return optax.sgd(kw["lr"])
        if name == "momentum":
            return optax.sgd(kw["lr"], momentum=kw["mu"])
        return optax.adam(kw["lr"], b1=kw["b1"], b2=kw["b2"],
                          eps=kw["eps"])

    def _flatten(self, tree: PyTree) -> np.ndarray:
        import jax

        leaves = jax.tree.leaves(tree)
        return np.concatenate(
            [np.asarray(l, np.float32).ravel() for l in leaves])

    def _unflatten(self, flat: np.ndarray) -> PyTree:
        import jax

        out, off = [], 0
        for shape, size, dtype in zip(self._shapes, self._sizes,
                                      self._dtypes):
            out.append(flat[off:off + size].reshape(shape).astype(dtype))
            off += size
        return jax.tree.unflatten(self._treedef, out)

    @property
    def params(self) -> PyTree:
        """The current parameters, as the original pytree."""
        return self._unflatten(self._flat)

    @property
    def rounds(self) -> int:
        return self._rounds

    def opt_state_bytes(self) -> int:
        """Optimizer-state bytes THIS WORKER holds — the redundancy the
        server mode eliminates (the BENCH_SERVEROPT headline)."""
        if self.mode == "server":
            return 0
        import jax

        return sum(int(np.asarray(l).nbytes)
                   for l in jax.tree.leaves(self._opt_state))

    def step(self, grads: PyTree, timeout: Optional[float] = 300.0
             ) -> PyTree:
        """Push one round's gradients; adopt the post-update params.

        Server mode: the pull IS the updated parameters (the server ran
        the step once, on the key's owner).  Local mode: the pull is the
        gradient sum and the identical optax step runs here."""
        flat_g = self._flatten(grads)
        if self._hier is not None:
            pulled = np.asarray(
                self._hier.push_pull_flat(self._key, flat_g,
                                          timeout=timeout),
                np.float32).ravel()
        else:
            handle = self._session.push_pull_async(self._key, flat_g)
            pulled = np.asarray(handle.wait(timeout), np.float32).ravel()
        if self.mode == "server":
            self._flat = pulled
        else:
            import jax.numpy as jnp
            import optax

            from ..common import devprof

            g = pulled
            if self._grad_scale != 1.0:
                # One weak-f32 scalar multiply, mirrored exactly by the
                # server's gscale leg.
                g = np.float32(self._grad_scale) * g
            # Device-plane hook (common/devprof.py): the local-mode
            # optimizer update is this trainer's on-device work (server
            # mode runs it on the PS tier, so there is nothing to
            # time).  np.asarray below already synchronizes, so the
            # step_end token needs no extra block.
            tok = devprof.step_begin()
            updates, self._opt_state = self._opt.update(
                jnp.asarray(g), self._opt_state,
                jnp.asarray(self._flat))
            self._flat = np.asarray(
                optax.apply_updates(jnp.asarray(self._flat), updates),
                np.float32)
            devprof.step_end(tok)
        self._rounds += 1
        return self.params

    def server_docs(self) -> dict:
        """The authoritative per-partition opt docs (param_version,
        slots_crc, ...) — empty in local mode."""
        if self.mode != "server":
            return {}
        return self._session.fetch_opt_docs(self._key)
