"""Data-parallel training: DistributedOptimizer and the jitted train step.

The reference wraps each framework's optimizer so that every gradient is
push_pull'd before the local update (reference: byteps/torch/__init__.py:
115-214, byteps/mxnet/__init__.py:74-92, byteps/tensorflow/__init__.py:
184-278).  The TPU-native equivalent wraps an optax GradientTransformation:
`update()` runs the partitioned, priority-ordered all-reduce from
ops.collectives over the mesh's dp axis (hierarchical over ici/dcn when the
mesh is two-level), then applies the inner transform.  Everything is traced
under jit — XLA overlaps the bucket collectives with backward compute, which
is the cross-barrier effect the reference builds by hand with threads + locks
(reference: torch/cross_barrier.py).

Bucket composition routes through the shared fusion planner
(common/fusion.py, via ops.collectives.BucketPlan): the in-graph plane and
the PS wire plane (push_pull_tree / AsyncPSTrainer) pack small leaves with
the same reverse-backprop-order algorithm, so a model's overlap behavior is
the same story on both planes and `bps.get_fusion_stats()` sees plan
activity from either.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common import devprof
from ..common.compat import shard_map as _shard_map
from ..common.config import get_config
from ..ops import collectives
from ..ops.compression import Compression, Compressor

PyTree = Any


@jax.tree_util.register_pytree_with_keys_class
class CompressionOptState:
    """Optax state slot holding per-bucket compressor state (EF error
    buffers, momentum, PRNG lanes) — the functional stand-in for the
    reference's mutable per-partition compressor objects
    (reference: operations.cc:380-385).

    `world` (static aux data) records how many per-worker copies the state
    currently holds; build_train_step tiles/validates it against the mesh's
    dp axis size so a default-constructed state is automatically expanded.
    """

    def __init__(self, comp: Any, world: int = 1):
        self.comp = comp
        self.world = world

    def tree_flatten_with_keys(self):
        return ((jax.tree_util.GetAttrKey("comp"), self.comp),), self.world

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux)

    def __repr__(self):
        return f"CompressionOptState(world={self.world})"

    def __eq__(self, other):
        return (isinstance(other, CompressionOptState)
                and other.world == self.world
                and jax.tree.structure(other.comp)
                == jax.tree.structure(self.comp))


def distributed_gradient_transform(
    axis_name: str = "dp",
    average: bool = True,
    compression: Optional[Compressor] = None,
    inter_compressor: Optional[Any] = None,
    partition_bytes: Optional[int] = None,
    hierarchical: bool = False,
    world: int = 1,
) -> optax.GradientTransformation:
    """An optax transform that all-reduces gradients across `axis_name`.

    `compression` is the framework-level cast (Compression.fp16 → bf16 wire
    format); `inter_compressor` is a byteps_tpu.ops.compressor instance
    (onebit/topk/...) applied per bucket on-device.

    `world` must be the dp axis size when a *stateful* inter_compressor is
    used on a multi-device mesh: compressor state (error-feedback buffers,
    PRNG lanes) is genuinely per-worker — like the reference's per-process
    compressor objects (operations.cc:380-385) — so init tiles each state
    buffer `world` times and build_train_step shards it over `axis_name`,
    giving every shard its own slice.
    """
    compression = compression or Compression.none

    def init_fn(params):
        if inter_compressor is not None:
            import jax.numpy as jnp
            from ..ops.compressor import init_compression_state
            # The bucket plan must match update_fn's, which bucketizes the
            # post-cast wire tree — so build state from the wire shapes,
            # not the raw params.
            wire_shapes = jax.eval_shape(
                lambda p: _tree_compress(p, compression)[0], params)
            zeros = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                                 wire_shapes)
            comp = init_compression_state(zeros, inter_compressor,
                                          partition_bytes)
            if world > 1:
                comp = _tile_state(comp, world)
            return CompressionOptState(comp, world=world)
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        wire, ctxs = _tree_compress(updates, compression)
        if inter_compressor is not None:
            from ..ops.compressor import compressed_tree_all_reduce
            reduced, new_comp = compressed_tree_all_reduce(
                wire, inter_compressor, state.comp, axis_name=axis_name,
                average=average, partition_bytes=partition_bytes)
            state = CompressionOptState(new_comp, world=state.world)
        elif hierarchical:
            reduced = collectives.hierarchical_tree_all_reduce(
                wire, average=average, partition_bytes=partition_bytes)
        else:
            reduced = collectives.bucketed_tree_all_reduce(
                wire, axis_name=axis_name, average=average,
                partition_bytes=partition_bytes)
        out = _tree_decompress(reduced, ctxs, compression)
        return out, state

    return optax.GradientTransformation(init_fn, update_fn)


def _tree_compress(tree, compression):
    leaves, treedef = jax.tree.flatten(tree)
    outs, ctxs = [], []
    for l in leaves:
        c, ctx = compression.compress(l)
        outs.append(c)
        ctxs.append(ctx)
    return jax.tree.unflatten(treedef, outs), ctxs


def _tree_decompress(tree, ctxs, compression):
    leaves, treedef = jax.tree.flatten(tree)
    outs = [compression.decompress(l, ctx) for l, ctx in zip(leaves, ctxs)]
    return jax.tree.unflatten(treedef, outs)


class DistributedGradientTransformation(NamedTuple):
    """optax-compatible (init/update duck type) transform that also records
    the `backward_passes_per_step` knob, so build_train_step can refuse the
    double-scaling combination with `accum_steps` (both would divide the
    gradient by N)."""

    init: Callable
    update: Callable
    backward_passes_per_step: int = 1


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    named_parameters: Any = None,  # accepted for API parity; unused in JAX
    compression: Optional[Compressor] = None,
    inter_compressor: Optional[Any] = None,
    axis_name: str = "dp",
    average: bool = True,
    partition_bytes: Optional[int] = None,
    hierarchical: bool = False,
    backward_passes_per_step: int = 1,
    world: int = 1,
) -> "DistributedGradientTransformation":
    """Wrap an optax optimizer so updates are preceded by distributed
    gradient push_pull — the JAX face of the reference's
    `bps.DistributedOptimizer`.

    `backward_passes_per_step > 1` scales gradients down to keep the average
    correct under gradient accumulation (reference exposes the same knob).

    The return value is an optax-compatible init/update pair, but a
    THREE-field NamedTuple (DistributedGradientTransformation) — use
    `.init`/`.update` attribute access, not 2-tuple unpacking.
    """
    del named_parameters
    chain = [distributed_gradient_transform(
        axis_name=axis_name, average=average, compression=compression,
        inter_compressor=inter_compressor, partition_bytes=partition_bytes,
        hierarchical=hierarchical, world=world)]
    if backward_passes_per_step > 1:
        chain.append(optax.scale(1.0 / backward_passes_per_step))
    chain.append(optimizer)
    chained = optax.chain(*chain)
    return DistributedGradientTransformation(
        chained.init, chained.update,
        backward_passes_per_step=backward_passes_per_step)


# ---------------------------------------------------------------------------
# Train-step builder: the canonical hot path.
# ---------------------------------------------------------------------------
def build_train_step(
    loss_fn: Callable[..., jax.Array],
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    axis_name: str = "dp",
    batch_spec: Optional[P] = None,
    donate: bool = True,
    accum_steps: int = 1,
) -> Callable:
    """Returns jitted `step(params, opt_state, batch) -> (params, opt_state,
    loss)` where:

      - params/opt_state are replicated across the mesh,
      - batch is sharded over `axis_name` (default P('dp') on axis 0),
      - gradients are computed per-shard and reduced by the optimizer's
        distributed transform (which must psum over `axis_name` — use
        DistributedOptimizer).

    `accum_steps > 1` splits each shard's batch into that many microbatches
    under `lax.scan` and averages their gradients before the ONE distributed
    update — gradient accumulation with a single all-reduce per step (the
    reference's `backward_passes_per_step` semantics, reference:
    torch/__init__.py:115-174, without its per-pass push_pull traffic).
    Peak activation memory drops to one microbatch's.

    This is the structural equivalent of the reference's
    backward-hook → push_pull → optimizer.step loop (reference:
    torch/__init__.py:140-174) collapsed into one compiled program.
    """
    if (axis_name == "dp" and "dp" not in mesh.shape
            and {"dcn_dp", "ici_dp"} <= set(mesh.axis_names)):
        # Two-level mesh from make_hierarchical_mesh: the batch shards
        # over BOTH dp levels and the loss pmean spans them, so the
        # canonical `build_train_step(loss, opt, make_hierarchical_mesh(),
        # DistributedOptimizer(..., hierarchical=True))` pod recipe works
        # without the caller naming internal axes.
        axis_name = ("dcn_dp", "ici_dp")
    if batch_spec is None:
        batch_spec = P(axis_name)
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    # Best-effort guard: the knob is only visible on a directly-passed
    # DistributedOptimizer.  If you re-wrap it (optax.chain(...)), the
    # guard can't see it — don't combine the two forms yourself.
    if (accum_steps > 1
            and getattr(optimizer, "backward_passes_per_step", 1) > 1):
        raise ValueError(
            "accum_steps and DistributedOptimizer(backward_passes_per_step)"
            " are alternative forms of the same averaging — combining them"
            " would divide the update by the product.  Use accum_steps for"
            " in-step (lax.scan) accumulation, or backward_passes_per_step"
            " when the training loop itself calls update() once per pass.")
    donate_argnums = (0, 1) if donate else ()

    def _value_and_grad(params, batch):
        if accum_steps == 1:
            return jax.value_and_grad(loss_fn)(params, batch)

        def split(x):
            if x.shape[0] % accum_steps:
                raise ValueError(
                    f"per-shard batch dim {x.shape[0]} is not divisible by "
                    f"accum_steps={accum_steps}")
            return x.reshape((accum_steps, x.shape[0] // accum_steps)
                             + x.shape[1:])

        micros = jax.tree.map(split, batch)

        # Accumulate in f32 regardless of the param/grad dtype: bf16
        # partial sums would round each step and break the equals-the-
        # full-batch-gradient contract as accum_steps grows.  Cast back to
        # the native grad dtype after averaging.
        def micro(carry, mb):
            loss_sum, g_sum = carry
            l, g = jax.value_and_grad(loss_fn)(params, mb)
            return (loss_sum + l.astype(jnp.float32),
                    jax.tree.map(lambda s, x: s + x.astype(jnp.float32),
                                 g_sum, g)), None

        init = (jnp.zeros((), jnp.float32),
                jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                             params))
        (loss_sum, g_sum), _ = jax.lax.scan(micro, init, micros)
        inv = 1.0 / accum_steps
        return loss_sum * inv, jax.tree.map(
            lambda g, p: (g * inv).astype(p.dtype), g_sum, params)

    if mesh.devices.size == 1:
        # Single-device fast path: the reference's non-distributed mode
        # builds a queue list with no PUSH/PULL (operations.cc:429-485); here
        # the whole step lowers to a plain jit — collectives trace as
        # identity under local_mode, so no sharding machinery or collective
        # dispatch overhead remains.
        def _local_step(params, opt_state, batch):
            with collectives.local_mode():
                loss, grads = _value_and_grad(params, batch)
                updates, opt_state = optimizer.update(grads, opt_state,
                                                      params)
                params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        jitted = jax.jit(_local_step, donate_argnums=donate_argnums)

        def local_call(params, opt_state, batch):
            opt_state = _retile_comp_state(opt_state, 1)
            # Device-plane hook (common/devprof.py): unarmed this is one
            # None check; armed it resolves cached FLOPs pre-dispatch
            # and syncs in step_end to record a true device step time.
            tok = devprof.step_begin(jitted, (params, opt_state, batch))
            out = jitted(params, opt_state, batch)
            devprof.step_end(tok, out)
            return out

        return local_call

    def _step(params, opt_state, batch):
        loss, grads = _value_and_grad(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # Per-shard losses -> global mean for reporting.
        loss = jax.lax.pmean(loss, axis_name)
        return params, opt_state, loss

    # Compressor state inside the opt state is per-worker (see
    # distributed_gradient_transform's `world`): those leaves are sharded
    # over the dp axis; everything else is replicated.  The specs depend on
    # the opt_state pytree structure, so the shard_map is built lazily on
    # first call and cached per structure.
    cache = {}
    axes = axis_name if isinstance(axis_name, tuple) else (axis_name,)
    dp_world = int(math.prod(mesh.shape.get(a, 1) for a in axes))

    def call(params, opt_state, batch):
        opt_state = _retile_comp_state(opt_state, dp_world)
        key = (jax.tree.structure(params), jax.tree.structure(opt_state))
        if key not in cache:
            state_specs = _opt_state_specs(opt_state, axis_name)
            sm = _shard_map(
                _step, mesh=mesh, in_specs=(P(), state_specs, batch_spec),
                out_specs=(P(), state_specs, P()), check_vma=False)
            cache[key] = jax.jit(sm, donate_argnums=donate_argnums)
        fn = cache[key]
        # Device-plane hook: same contract as the single-device path.
        tok = devprof.step_begin(fn, (params, opt_state, batch))
        out = fn(params, opt_state, batch)
        devprof.step_end(tok, out)
        return out

    return call


def _tile_state(comp: PyTree, world: int) -> PyTree:
    return jax.tree.map(
        lambda l: jnp.tile(l, (world,) + (1,) * (l.ndim - 1))
        if l.ndim >= 1 else l, comp)


def _retile_comp_state(opt_state: PyTree, dp_world: int) -> PyTree:
    """Expand (or validate) per-worker compressor state against the mesh's
    dp axis size, so a default-constructed (world=1) state just works on any
    mesh and a mismatched one fails loudly instead of silently slicing PRNG
    lanes / EF buffers."""
    def fix(node):
        if not isinstance(node, CompressionOptState):
            return node
        if node.world == dp_world:
            return node
        if node.world == 1:
            return CompressionOptState(_tile_state(node.comp, dp_world),
                                       world=dp_world)
        raise ValueError(
            f"compressor state was initialised for world={node.world} but "
            f"the mesh dp axis has {dp_world} shards; re-init the optimizer "
            f"state (opt.init) for this mesh")
    return jax.tree.map(
        fix, opt_state,
        is_leaf=lambda x: isinstance(x, CompressionOptState))


def _opt_state_specs(opt_state: PyTree, axis_name: str) -> PyTree:
    """P(axis_name) for per-worker compressor-state leaves (identified by
    sitting under a CompressionOptState), P() for everything else."""
    from jax.tree_util import tree_flatten_with_path

    paths_leaves, treedef = tree_flatten_with_path(opt_state)
    specs = []
    for path, leaf in paths_leaves:
        in_comp = any(getattr(k, "name", None) == "comp" for k in path)
        if in_comp and getattr(leaf, "ndim", 0) >= 1:
            specs.append(P(axis_name))
        else:
            specs.append(P())
    return jax.tree.unflatten(treedef, specs)
