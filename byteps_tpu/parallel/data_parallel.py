"""Data-parallel training: DistributedOptimizer and the jitted train step.

The reference wraps each framework's optimizer so that every gradient is
push_pull'd before the local update (reference: byteps/torch/__init__.py:
115-214, byteps/mxnet/__init__.py:74-92, byteps/tensorflow/__init__.py:
184-278).  The TPU-native equivalent wraps an optax GradientTransformation:
`update()` runs the partitioned, priority-ordered all-reduce from
ops.collectives over the mesh's dp axis (hierarchical over ici/dcn when the
mesh is two-level), then applies the inner transform.  Everything is traced
under jit — XLA overlaps the bucket collectives with backward compute, which
is the cross-barrier effect the reference builds by hand with threads + locks
(reference: torch/cross_barrier.py).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common.config import get_config
from ..ops import collectives
from ..ops.compression import Compression, Compressor

PyTree = Any


def distributed_gradient_transform(
    axis_name: str = "dp",
    average: bool = True,
    compression: Optional[Compressor] = None,
    inter_compressor: Optional[Any] = None,
    partition_bytes: Optional[int] = None,
    hierarchical: bool = False,
) -> optax.GradientTransformation:
    """An optax transform that all-reduces gradients across `axis_name`.

    `compression` is the framework-level cast (Compression.fp16 → bf16 wire
    format); `inter_compressor` is a byteps_tpu.ops.compressor instance
    (onebit/topk/...) applied per bucket on-device.
    """
    compression = compression or Compression.none

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        wire, ctxs = _tree_compress(updates, compression)
        if inter_compressor is not None:
            try:
                from ..ops.compressor import compressed_tree_all_reduce
            except ImportError as e:
                raise RuntimeError(
                    "inter_compressor requires byteps_tpu.ops.compressor, "
                    "which is missing from this build") from e
            reduced = compressed_tree_all_reduce(
                wire, inter_compressor, axis_name=axis_name, average=average,
                partition_bytes=partition_bytes)
        elif hierarchical:
            reduced = collectives.hierarchical_tree_all_reduce(
                wire, average=average, partition_bytes=partition_bytes)
        else:
            reduced = collectives.bucketed_tree_all_reduce(
                wire, axis_name=axis_name, average=average,
                partition_bytes=partition_bytes)
        out = _tree_decompress(reduced, ctxs, compression)
        return out, state

    return optax.GradientTransformation(init_fn, update_fn)


def _tree_compress(tree, compression):
    leaves, treedef = jax.tree.flatten(tree)
    outs, ctxs = [], []
    for l in leaves:
        c, ctx = compression.compress(l)
        outs.append(c)
        ctxs.append(ctx)
    return jax.tree.unflatten(treedef, outs), ctxs


def _tree_decompress(tree, ctxs, compression):
    leaves, treedef = jax.tree.flatten(tree)
    outs = [compression.decompress(l, ctx) for l, ctx in zip(leaves, ctxs)]
    return jax.tree.unflatten(treedef, outs)


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    named_parameters: Any = None,  # accepted for API parity; unused in JAX
    compression: Optional[Compressor] = None,
    inter_compressor: Optional[Any] = None,
    axis_name: str = "dp",
    average: bool = True,
    partition_bytes: Optional[int] = None,
    hierarchical: bool = False,
    backward_passes_per_step: int = 1,
) -> optax.GradientTransformation:
    """Wrap an optax optimizer so updates are preceded by distributed
    gradient push_pull — the JAX face of the reference's
    `bps.DistributedOptimizer`.

    `backward_passes_per_step > 1` scales gradients down to keep the average
    correct under gradient accumulation (reference exposes the same knob).
    """
    del named_parameters
    chain = [distributed_gradient_transform(
        axis_name=axis_name, average=average, compression=compression,
        inter_compressor=inter_compressor, partition_bytes=partition_bytes,
        hierarchical=hierarchical)]
    if backward_passes_per_step > 1:
        chain.append(optax.scale(1.0 / backward_passes_per_step))
    chain.append(optimizer)
    return optax.chain(*chain)


# ---------------------------------------------------------------------------
# Train-step builder: the canonical hot path.
# ---------------------------------------------------------------------------
def build_train_step(
    loss_fn: Callable[..., jax.Array],
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    axis_name: str = "dp",
    batch_spec: Optional[P] = None,
    donate: bool = True,
) -> Callable:
    """Returns jitted `step(params, opt_state, batch) -> (params, opt_state,
    loss)` where:

      - params/opt_state are replicated across the mesh,
      - batch is sharded over `axis_name` (default P('dp') on axis 0),
      - gradients are computed per-shard and reduced by the optimizer's
        distributed transform (which must psum over `axis_name` — use
        DistributedOptimizer).

    This is the structural equivalent of the reference's
    backward-hook → push_pull → optimizer.step loop (reference:
    torch/__init__.py:140-174) collapsed into one compiled program.
    """
    if batch_spec is None:
        batch_spec = P(axis_name)

    replicated = NamedSharding(mesh, P())

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), P(), batch_spec), out_specs=(P(), P(), P()),
        check_vma=False)
    def _step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # Per-shard losses -> global mean for reporting.
        loss = jax.lax.pmean(loss, axis_name)
        return params, opt_state, loss

    donate_argnums = (0, 1) if donate else ()
    return jax.jit(_step, donate_argnums=donate_argnums)
