"""Hierarchical reduction: in-graph psum intra-slice, PS inter-slice.

The PS tier treats every chip as a lone worker: on an S-chip slice, S
workers each push the full gradient over the wire and pull the full sum
back, so the PS moves S× the bytes it needs to.  Real TPU pods compose
the two reduction planes instead (arXiv 2204.06514 "Scalable Training of
Language Models using JAX pjit and TPUv4"): XLA's native collectives
reduce *inside* a slice over ICI, and only one designated leader per
slice talks across slices over DCN.  This module is that composition for
the PS tier:

  1. the workers of one slice reduce their gradients in-graph — a
     ``psum`` under ``shard_map`` on the slice's device mesh (routed
     through :mod:`byteps_tpu.common.compat`, so both JAX spellings
     work);
  2. exactly ONE leader per slice runs the wire ``push_pull`` (riding
     the existing fusion planner and ``PSSession.push_pull_group``
     unchanged — the server sums the per-slice sums, which equals the
     sum over every chip);
  3. the pulled sum (or, under ``ServerOptTrainer``, the pulled
     parameters) broadcasts back to the slice's members in-graph.

Per-slice wire bytes drop by the slice size on BOTH legs: followers
never touch the data plane at all.

Topology & leadership
---------------------
Slices are contiguous worker-id ranges: worker ``w`` belongs to slice
``w // slice_size`` (the DMLC_WORKER_ID convention — chips of one host
get consecutive ids).  The leader of a slice is its LOWEST ALIVE member
under the current membership epoch (:meth:`PSSession.slice_leader`), so
leadership fails over inside the slice when the leader is evicted, and
an entirely-departed slice simply stops being expected — the server's
round completion counts *slices*, not chips (``core/server.cc``
``RoundComplete`` under ``BYTEPS_TPU_SLICE_SIZE``), expressed through
the same epoch/``round_members`` machinery elastic membership already
uses.  ``slice_size=1`` (the default) degenerates to flat mode exactly:
every worker is the sole member and leader of its own slice, every
reduce is the identity, and the wire is byte-identical to today.

Colocation contract
-------------------
Intra-slice reduction is in-graph, so a slice's members must share one
process (the JAX single-controller model: one process drives the
slice's devices; in tests, worker threads each driving one CPU device).
The process-wide :func:`get_slice_group` registry hands every member
the same :class:`SliceGroup`; a member that never shows up surfaces as
a loud ``TimeoutError`` naming the missing ids, never a silent hang.

Exactness: the slice reduce reassociates the float sum ((g0+g1)+(g2+g3)
instead of the server's arrival order), so flat-vs-hierarchical
trajectories are bit-identical exactly when the sums are (integer-valued
f32 gradients, or any value set whose sum is exact) — the same law
elastic re-finalization already documents for merge order.

Enable with ``BYTEPS_TPU_HIERARCHY=1`` + ``BYTEPS_TPU_SLICE_SIZE=S`` on
workers AND servers (the server needs the slice size for round
completion).  Off by default; an unarmed run constructs none of this
and the wire is byte-identical to flat mode (recording-stub asserted in
tests/test_hierarchy.py).
"""

from __future__ import annotations

import functools
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

__all__ = [
    "slice_of", "slice_members", "elect_leader", "intra_slice_psum",
    "SliceGroup", "get_slice_group", "reset_slice_groups",
    "HierarchicalReducer", "maybe_reducer",
]


# ---------------------------------------------------------------------------
# Topology laws (shared with server.cc RoundComplete and
# PSSession.slice_leader — one definition per side, same math)
# ---------------------------------------------------------------------------
def slice_of(worker_id: int, slice_size: int) -> int:
    """The slice a worker id belongs to: contiguous ranges of
    ``slice_size`` ids (slice 0 = ids [0, S), slice 1 = [S, 2S), ...)."""
    s = max(1, int(slice_size))
    return int(worker_id) // s


def slice_members(slice_id: int, slice_size: int,
                  world: Optional[int] = None) -> List[int]:
    """The worker ids of one slice, clipped to ``world`` when given (the
    last slice of a non-multiple world is short, never padded)."""
    s = max(1, int(slice_size))
    lo = int(slice_id) * s
    hi = lo + s
    if world is not None:
        hi = min(hi, int(world))
    return list(range(lo, hi))


def elect_leader(members: Sequence[int],
                 alive: Optional[Sequence[int]] = None) -> Optional[int]:
    """The slice leader: the LOWEST ALIVE member (None = launch set, all
    alive).  Returns None when the whole slice has departed — the server
    then stops expecting the slice at the next epoch boundary, so "a
    slice leaving reads as as many chips leaving"."""
    pool = [int(m) for m in members]
    if alive is not None:
        live = {int(a) for a in alive}
        pool = [m for m in pool if m in live]
    return min(pool) if pool else None


# ---------------------------------------------------------------------------
# In-graph intra-slice reduction
# ---------------------------------------------------------------------------
@functools.lru_cache(maxsize=8)
def _psum_fn(mesh):
    """Cached jitted shard_map psum over the mesh's single axis — a
    fresh lambda per call would miss jax.jit's cache (keyed on function
    identity) and retrace every slice reduce."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..common import compat

    axis = mesh.axis_names[-1]

    def body(x):
        return jax.lax.psum(x, axis)

    return jax.jit(compat.shard_map(
        body, mesh=mesh, in_specs=P(axis), out_specs=P()))


def intra_slice_psum(stacked: np.ndarray, mesh=None) -> np.ndarray:
    """Sum ``stacked`` (members, n) over axis 0 IN-GRAPH: one member row
    per device of the slice mesh, reduced by ``psum`` under ``shard_map``
    (through the compat shims, so both the ``jax.shard_map`` and the
    0.4.x ``jax.experimental.shard_map`` spellings work).

    Falls back to a deterministic host sum (ascending member order) when
    the process has fewer addressable devices than members — the values
    are identical for exactly-summable gradients either way; only the
    engine differs.
    """
    stacked = np.ascontiguousarray(stacked, dtype=np.float32)
    n = stacked.shape[0]
    if n == 1:
        return stacked[0]
    if mesh is None:
        mesh = _default_slice_mesh(n)
    if mesh is None:
        return np.add.reduce(stacked, axis=0, dtype=np.float32)
    return np.asarray(_psum_fn(mesh)(stacked))[0]


@functools.lru_cache(maxsize=8)
def _default_slice_mesh(n: int):
    """One mesh per member count, cached so every reduce of the same
    width reuses the same Mesh object (and _psum_fn's jit cache)."""
    from .mesh import make_slice_mesh
    return make_slice_mesh(n)


# ---------------------------------------------------------------------------
# SliceGroup: the rendezvous the slice's colocated members meet at
# ---------------------------------------------------------------------------
_UNSET = object()


class SliceGroup:
    """In-process rendezvous for the workers of ONE slice.

    Two channels, both keyed by a caller-supplied round key (the
    declared key, or a tuple of them for a fused group) plus a
    per-member sequence counter, so concurrent rounds on different keys
    — and handles synchronized out of call order — can never cross:

    - :meth:`reduce`: every member contributes its arrays; all members
      return the SAME slice-summed arrays (the in-graph psum ran once).
    - :meth:`broadcast`: the leader publishes a value; every member
      (including the leader) returns it.

    A member that never arrives fails the round with a ``TimeoutError``
    naming the missing ids — the colocation contract breaking loudly.
    """

    def __init__(self, slice_id: int, members: Sequence[int], mesh=None,
                 timeout_s: float = 120.0):
        self.slice_id = int(slice_id)
        self.members = sorted(int(m) for m in members)
        if not self.members:
            raise ValueError("a SliceGroup needs at least one member")
        self.mesh = mesh
        self.timeout_s = float(timeout_s)
        self._cv = threading.Condition()
        self._seq: Dict[tuple, int] = {}     # (chan, key, wid) -> next seq
        self._rounds: Dict[tuple, dict] = {}  # (chan, key, seq) -> state

    def __len__(self) -> int:
        return len(self.members)

    def _next_seq(self, chan: str, key, wid: int) -> int:
        k = (chan, key, wid)
        s = self._seq.get(k, 0)
        self._seq[k] = s + 1
        return s

    def _round(self, chan: str, key, seq: int) -> dict:
        return self._rounds.setdefault(
            (chan, key, seq),
            {"contrib": {}, "result": _UNSET, "taken": set()})

    def _finish(self, chan: str, key, seq: int, st: dict,
                wid: int) -> Any:
        st["taken"].add(wid)
        if len(st["taken"]) == len(self.members):
            del self._rounds[(chan, key, seq)]
        return st["result"]

    def _await(self, st: dict, chan: str, key) -> None:
        import time
        deadline = time.monotonic() + self.timeout_s
        while st["result"] is _UNSET:
            left = deadline - time.monotonic()
            if left <= 0 or not self._cv.wait(timeout=min(1.0, left)):
                if st["result"] is not _UNSET:
                    return
                if time.monotonic() >= deadline:
                    here = sorted(st["contrib"]) or sorted(st["taken"])
                    missing = [m for m in self.members if m not in here]
                    raise TimeoutError(
                        f"slice {self.slice_id} {chan} round on key "
                        f"{key!r} timed out after {self.timeout_s:.0f}s "
                        f"waiting on member(s) {missing} (slice members "
                        f"must share this process — see "
                        f"docs/architecture.md 'Hierarchical reduction')")

    def reduce(self, worker_id: int, key, arrays: List[np.ndarray]
               ) -> List[np.ndarray]:
        """Rendezvous all members, sum their arrays element-wise via the
        in-graph psum, return the summed list to every member."""
        flats = [np.ascontiguousarray(a, dtype=np.float32).ravel()
                 for a in arrays]
        with self._cv:
            seq = self._next_seq("reduce", key, worker_id)
            st = self._round("reduce", key, seq)
            st["contrib"][worker_id] = flats
            if len(st["contrib"]) == len(self.members):
                # Last arrival runs the reduction for everyone: ONE
                # concatenated psum per round, not one per array.
                per_member = [st["contrib"][m] for m in self.members]
                sizes = [f.size for f in per_member[0]]
                stacked = np.stack(
                    [np.concatenate(fs) if len(fs) > 1 else fs[0]
                     for fs in per_member])
                summed = intra_slice_psum(stacked, mesh=self.mesh)
                out, off = [], 0
                for a, n in zip(arrays, sizes):
                    out.append(summed[off:off + n]
                               .reshape(np.shape(a)).astype(np.float32))
                    off += n
                st["result"] = out
                st["contrib"].clear()    # drop member refs promptly
                self._cv.notify_all()
            else:
                self._await(st, "reduce", key)
            return self._finish("reduce", key, seq, st, worker_id)

    def broadcast(self, worker_id: int, key, value=_UNSET) -> Any:
        """Leader publishes ``value``; every member returns it.  Callers
        without a value block until the leader's arrives."""
        with self._cv:
            seq = self._next_seq("bcast", key, worker_id)
            st = self._round("bcast", key, seq)
            if value is not _UNSET:
                st["result"] = value
                self._cv.notify_all()
            else:
                self._await(st, "bcast", key)
            return self._finish("bcast", key, seq, st, worker_id)

    def poll(self, worker_id: int, key) -> bool:
        """True when this member's NEXT broadcast round already has its
        value (non-consuming — the follower-side handle-poll signal)."""
        with self._cv:
            seq = self._seq.get(("bcast", key, worker_id), 0)
            st = self._rounds.get(("bcast", key, seq))
            return st is not None and st["result"] is not _UNSET


# Process-wide registry: colocated worker threads constructing reducers
# for the same slice meet at the same group object.
_groups_lock = threading.Lock()
_groups: Dict[tuple, SliceGroup] = {}


def get_slice_group(slice_id: int, members: Sequence[int], mesh=None,
                    timeout_s: float = 120.0) -> SliceGroup:
    """The process-shared SliceGroup for (slice_id, members) — created on
    first request, returned to every later member."""
    key = (int(slice_id), tuple(sorted(int(m) for m in members)))
    with _groups_lock:
        g = _groups.get(key)
        if g is None:
            g = SliceGroup(slice_id, members, mesh=mesh,
                           timeout_s=timeout_s)
            _groups[key] = g
        return g


def reset_slice_groups() -> None:
    """Drop the registry (tests; a fresh job must not meet a dead
    group's counters)."""
    with _groups_lock:
        _groups.clear()


def drop_slice_group(group: SliceGroup) -> None:
    """Retire ONE group from the registry (api.shutdown): a later
    re-init in the same process must get a fresh group with fresh seq
    counters — a failed round can leave members' counters desynced —
    while groups other in-process workers still hold stay untouched."""
    with _groups_lock:
        for k, g in list(_groups.items()):
            if g is group:
                del _groups[k]


# ---------------------------------------------------------------------------
# HierarchicalReducer: one worker's view of the two-plane reduction
# ---------------------------------------------------------------------------
class _LeaderHandle:
    """Leader-side round handle: wait the wire handle, broadcast the
    pulled value to the slice, return it."""

    carried_wire = True     # this worker's round produced wire traffic

    def __init__(self, reducer: "HierarchicalReducer", key, inner):
        self._r = reducer
        self._key = key
        self._inner = inner

    def done(self) -> bool:
        return self._inner.done()

    def wait(self, timeout: Optional[float] = 300.0) -> np.ndarray:
        try:
            out = np.asarray(self._inner.wait(timeout), np.float32)
        except Exception as e:
            # Followers are blocked on the broadcast: a leader-side wire
            # failure must propagate to the WHOLE slice, not strand it.
            self._r.group.broadcast(self._r.worker_id, self._key,
                                    value=_WireError(e))
            raise
        self._r.group.broadcast(self._r.worker_id, self._key, value=out)
        return out


class _FollowerHandle:
    """Follower-side round handle: the pulled value arrives via the
    leader's broadcast — zero wire traffic on this worker."""

    carried_wire = False

    def __init__(self, reducer: "HierarchicalReducer", key):
        self._r = reducer
        self._key = key

    def done(self) -> bool:
        return self._r.group.poll(self._r.worker_id, self._key)

    def wait(self, timeout: Optional[float] = 300.0) -> np.ndarray:
        out = self._r.group.broadcast(self._r.worker_id, self._key)
        if isinstance(out, _WireError):
            raise RuntimeError(
                f"slice {self._r.slice_id} leader "
                f"{self._r.leader()} wire round failed: "
                f"{out.exc}") from out.exc
        return out


class _WireError:
    """Broadcast payload marking a leader-side wire failure."""

    def __init__(self, exc: Exception):
        self.exc = exc


class HierarchicalReducer:
    """One worker's hierarchical push_pull plane.

    ``dispatch_round`` is the trainer face (one flat vector per round);
    ``reduce_payloads``/``publish_outs``/``await_outs`` are the
    fused-tree face api.py rides (the leader keeps the existing
    fusion-planner + ``push_pull_group`` dispatch verbatim).
    """

    def __init__(self, session, worker_id: int, slice_size: int,
                 world: Optional[int] = None, group: Optional[SliceGroup]
                 = None, mesh=None, timeout_s: float = 120.0):
        self.session = session
        self.worker_id = int(worker_id)
        self.slice_size = max(1, int(slice_size))
        self.world = int(world) if world else None
        self.slice_id = slice_of(self.worker_id, self.slice_size)
        members = slice_members(self.slice_id, self.slice_size, self.world)
        self.group = group or get_slice_group(
            self.slice_id, members, mesh=mesh, timeout_s=timeout_s)
        self._lock = threading.Lock()
        self.stats = {
            "leader_rounds": 0,      # wire rounds this worker ran
            "follower_rounds": 0,    # wire rounds this worker skipped
            "intra_reduces": 0,      # in-graph slice reductions joined
            "wire_bytes_saved": 0,   # push+pull payload bytes not sent
        }
        self._update_gauges()

    # -- leadership ---------------------------------------------------------
    def leader(self) -> Optional[int]:
        """The CURRENT leader of this worker's slice, elected from the
        session's last observed membership epoch (client.py owns the
        election so it rides the same view rounds are pinned to)."""
        fn = getattr(self.session, "slice_leader", None)
        if fn is not None:
            return fn(self.slice_size, world=self.world)
        return elect_leader(self.group.members)

    @property
    def is_leader(self) -> bool:
        return self.leader() == self.worker_id

    # -- trainer face: one flat vector per round ----------------------------
    def dispatch_round(self, key, flat: np.ndarray, seed: bool = False,
                       priority: int = 0,
                       leader_dispatch: Optional[Callable] = None):
        """One hierarchical round: slice-reduce ``flat`` in-graph, the
        leader dispatches the reduced vector on the wire, everyone gets
        a handle whose ``.wait()`` is the pulled value.

        ``seed=True`` skips the reduce — a seed is the initial weights,
        identical on every member by contract, and summing S copies
        would corrupt the store.  ``leader_dispatch(reduced) -> handle``
        overrides the wire leg (AsyncPSTrainer's fused chunk layout);
        the default is a plain ``session.push_pull_async``.
        """
        flat = np.ascontiguousarray(flat, dtype=np.float32).ravel()
        if seed or len(self.group) == 1:
            reduced = flat
        else:
            reduced = self.group.reduce(self.worker_id, key, [flat])[0]
            with self._lock:
                self.stats["intra_reduces"] += 1
        if self.is_leader:
            try:
                if leader_dispatch is None:
                    inner = self.session.push_pull_async(
                        key, reduced, priority=priority, seed=seed)
                else:
                    inner = leader_dispatch(reduced)
            except Exception as e:
                # Followers are already past the reduce, blocked on the
                # broadcast: a stage-time failure must fail the slice's
                # round, not strand it until the rendezvous timeout.
                self.group.broadcast(self.worker_id, key,
                                     value=_WireError(e))
                raise
            with self._lock:
                self.stats["leader_rounds"] += 1
            self._update_gauges()
            return _LeaderHandle(self, key, inner)
        with self._lock:
            self.stats["follower_rounds"] += 1
            # Both legs skipped: the push payload AND the pull reply.
            self.stats["wire_bytes_saved"] += 2 * int(flat.nbytes)
        self._record_saved(2 * int(flat.nbytes))
        self._update_gauges()
        return _FollowerHandle(self, key)

    def push_pull_flat(self, key, flat: np.ndarray, seed: bool = False,
                       timeout: Optional[float] = 300.0) -> np.ndarray:
        """Synchronous :meth:`dispatch_round` (the ServerOptTrainer
        shape: the pull IS the updated parameters there)."""
        return self.dispatch_round(key, flat, seed=seed).wait(timeout)

    # -- fused-tree face (api._fused_tree_push_pull) ------------------------
    def reduce_payloads(self, key, payloads: List[np.ndarray]
                        ) -> List[np.ndarray]:
        """Slice-reduce every dispatch unit's raw f32 payload in ONE
        in-graph psum, BEFORE the leader's wire compression — the codec
        then encodes the slice sum once instead of S gradients."""
        if len(self.group) == 1:
            return [np.ascontiguousarray(p, dtype=np.float32).ravel()
                    for p in payloads]
        out = self.group.reduce(self.worker_id, key, list(payloads))
        with self._lock:
            self.stats["intra_reduces"] += 1
        return out

    def publish_outs(self, key, outs: List[np.ndarray]) -> None:
        """Leader side: hand the round's decompressed, averaged unit
        outputs to the slice."""
        self.group.broadcast(self.worker_id, key, value=list(outs))
        with self._lock:
            self.stats["leader_rounds"] += 1
        self._update_gauges()

    def publish_failure(self, key, exc: Exception) -> None:
        """Leader side: fail the slice's round loudly instead of
        stranding followers on a broadcast that never comes."""
        self.group.broadcast(self.worker_id, key, value=_WireError(exc))

    def await_outs(self, key, skipped_bytes: int = 0) -> List[np.ndarray]:
        """Follower side: receive the round's unit outputs;
        ``skipped_bytes`` is the payload this worker did NOT push (the
        pull leg is counted as the same size)."""
        with self._lock:
            self.stats["follower_rounds"] += 1
            self.stats["wire_bytes_saved"] += 2 * int(skipped_bytes)
        self._record_saved(2 * int(skipped_bytes))
        self._update_gauges()
        out = self.group.broadcast(self.worker_id, key)
        if isinstance(out, _WireError):
            raise RuntimeError(
                f"slice {self.slice_id} leader {self.leader()} wire "
                f"round failed: {out.exc}") from out.exc
        return out

    def verify_topology(self) -> Optional[str]:
        """Cross-check this worker's slice size against the server tier's
        (CMD_STATS carries it).  Returns a human-readable mismatch
        description, or None when consistent / unverifiable.

        The mismatch's symptom without this check is the worst kind: a
        leaders-only round against a flat server just hangs until the
        wait timeout, naming nobody.  Called by api.init() (logged as an
        ERROR); direct-session users can call it themselves."""
        try:
            st = self.session.server_stats()
        except Exception:
            return None     # stats unreachable ≠ misconfigured
        srv = int(st.get("slice_size", 1))
        if srv == self.slice_size:
            return None
        return (f"worker slice_size={self.slice_size} but the server "
                f"tier runs slice_size={srv}"
                + (" (no BYTEPS_TPU_SLICE_SIZE on the servers, or a "
                   "pre-hierarchy server build)" if srv == 1 else "")
                + " — rounds will wait on pushes that never come; set "
                  "the SAME BYTEPS_TPU_SLICE_SIZE on workers and "
                  "servers (docs/env.md)")

    # -- observability ------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            s = dict(self.stats)
        s.update(armed=True, worker_id=self.worker_id,
                 slice_id=self.slice_id, slice_size=self.slice_size,
                 members=list(self.group.members), leader=self.leader(),
                 is_leader=self.is_leader)
        return s

    def _record_saved(self, nbytes: int) -> None:
        from ..common import telemetry
        telemetry.record_hierarchy_saved(nbytes)

    def _update_gauges(self) -> None:
        from ..common import telemetry
        telemetry.update_hierarchy(
            slice_id=self.slice_id, slice_size=self.slice_size,
            is_leader=self.is_leader,
            members=len(self.group.members))


def maybe_reducer(session, worker_id: Optional[int] = None,
                  world: Optional[int] = None
                  ) -> Optional[HierarchicalReducer]:
    """A HierarchicalReducer when the env opts in
    (``BYTEPS_TPU_HIERARCHY=1``), else None — the trainers' and api.py's
    one-line opt-in.  Reads ``BYTEPS_TPU_SLICE_SIZE`` for the topology;
    worker id / world default to the session's id and the config
    launch count."""
    import os

    if os.environ.get("BYTEPS_TPU_HIERARCHY", "0") != "1":
        return None
    if session is None:
        return None
    from ..common.config import get_config
    cfg = get_config()
    slice_size = int(os.environ.get("BYTEPS_TPU_SLICE_SIZE")
                     or cfg.slice_size or 1)
    wid = session.worker_id if worker_id is None else int(worker_id)
    w = cfg.num_worker if world is None else int(world)
    return HierarchicalReducer(session, wid, slice_size, world=w)
