"""GSPMD sharded training: the pjit/jit + NamedSharding path.

The reference has exactly one parallelism strategy — DP with hand-built
communication (SURVEY §2.6).  On TPU the idiomatic generalisation is to
annotate parameter and batch shardings over a named mesh and let XLA insert
the collectives: DP gradient reduction becomes the psum GSPMD derives from a
dp-sharded batch against replicated params; TP comes from Megatron-style
column/row PartitionSpecs on the weights (models/transformer.param_specs);
SP shards the sequence dimension.  This module packages that recipe.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PyTree = Any


def _is_spec(x):
    return isinstance(x, P)


def make_param_shardings(mesh: Mesh, specs: PyTree) -> PyTree:
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=_is_spec)


def shard_params(params: PyTree, mesh: Mesh, specs: PyTree) -> PyTree:
    """Place a param pytree onto the mesh under `specs` (PartitionSpec
    tree with the same structure)."""
    shardings = make_param_shardings(mesh, specs)
    return jax.tree.map(jax.device_put, params, shardings)


def opt_state_specs(optimizer: optax.GradientTransformation, params: PyTree,
                    specs: PyTree) -> PyTree:
    """Derive PartitionSpecs for the optimizer state.

    Optimizer state trees (adam mu/nu, momentum buffers) embed copies of the
    params tree, so each state leaf is matched to its param by PATH SUFFIX
    — e.g. state path (..., 'mu', 'layers', 'wq') matches param path
    ('layers', 'wq').  Shape matching alone is ambiguous (wq and wo share a
    shape but not a layout).  Unmatched leaves (step counters, scalars)
    replicate."""
    state_shape = jax.eval_shape(optimizer.init, params)
    return _opt_state_specs_from_shape(state_shape, params, specs)


def _opt_state_specs_from_shape(state_shape: PyTree, params: PyTree,
                                specs: PyTree) -> PyTree:
    from jax.tree_util import tree_flatten_with_path

    def key_id(k):
        return getattr(k, "key", getattr(k, "name", getattr(k, "idx", None)))

    param_paths, _ = tree_flatten_with_path(params)
    spec_leaves = jax.tree.leaves(specs, is_leaf=_is_spec)
    by_path = {tuple(key_id(k) for k in path): (leaf.shape, spec)
               for (path, leaf), spec in zip(param_paths, spec_leaves)}

    state_paths, treedef = tree_flatten_with_path(state_shape)
    out = []
    for path, leaf in state_paths:
        ids = tuple(key_id(k) for k in path)
        spec = P()
        for start in range(len(ids)):
            hit = by_path.get(ids[start:])
            if hit is not None and hit[0] == leaf.shape:
                spec = hit[1]
                break
        out.append(spec)
    return jax.tree.unflatten(treedef, out)


def zero1_opt_specs(optimizer: optax.GradientTransformation, params: PyTree,
                    mesh: Mesh, param_specs: PyTree,
                    dp_axis: str = "dp",
                    min_shard_elems: int = 1024) -> PyTree:
    """ZeRO-1 PartitionSpecs: optimizer state sharded over the dp axis.

    Plain DP replicates the optimizer state on every chip; for Adam that
    is 8 bytes/param of f32 moments per replica — the single largest HBM
    cost at scale (measured: llama_1b's ~9.3 GB of Adam state OOMs a
    16 GB chip that fits the params themselves, docs/performance.md).
    ZeRO-1 / XLA weight-update sharding (PAPERS.md: "Automatic
    Cross-Replica Sharding of Weight Update in Data-Parallel Training")
    stores 1/dp of each moment per replica instead: each state leaf that
    matches its param's spec gains the dp axis on its first
    not-yet-sharded, dp-divisible dimension, and XLA partitions the
    weight-update computation to match — lowering the DP all-reduce into
    reduce-scatter (sharded update math) + all-gather (updated params),
    the same wire bytes as a ring all-reduce.

    Leaves smaller than `min_shard_elems` (step counters, scalars, tiny
    vectors) and leaves with no dp-divisible free axis stay as derived by
    `opt_state_specs` — sharding them would cost more in collective
    latency than the bytes saved.

    On a mesh without `dp_axis` this raises: meshes with differently
    named data axes (e.g. `make_hierarchical_mesh`'s 'ici_dp'/'dcn_dp')
    must name the axis explicitly, or ZeRO-1 would silently no-op and
    the state would replicate — the OOM the caller asked to avoid.  An
    axis of size 1 (degenerate single-replica world) is a valid no-op.
    """
    _check_axis(mesh, dp_axis, "zero1")
    state_shape = jax.eval_shape(optimizer.init, params)
    base = _opt_state_specs_from_shape(state_shape, params, param_specs)
    return _shard_free_axis(base, state_shape, mesh, dp_axis,
                            min_shard_elems)


def _check_axis(mesh: Mesh, axis: str, who: str) -> None:
    """Raise on a mesh without the named axis — silently no-opping would
    replicate the very tensors the caller asked to shard (hierarchical
    meshes name their data axes 'ici_dp'/'dcn_dp', not 'dp')."""
    if axis not in mesh.axis_names:
        raise ValueError(
            f"{who} dp_axis={axis!r} is not a mesh axis "
            f"(mesh axes: {mesh.axis_names}); on a hierarchical mesh "
            f"pass the data axis explicitly, e.g. dp_axis='ici_dp'")


def _shard_free_axis(specs: PyTree, shapes: PyTree, mesh: Mesh,
                     dp_axis: str, min_shard_elems: int) -> PyTree:
    """Upgrade each spec with `dp_axis` on its leaf's first unsharded,
    dp-divisible dimension; leaves already using the axis, scalars, and
    leaves under `min_shard_elems` pass through unchanged."""
    dp = mesh.shape[dp_axis]
    if dp <= 1:
        return specs

    def upgrade(spec: P, leaf) -> P:
        if leaf.ndim == 0 or leaf.size < min_shard_elems:
            return spec
        entries = list(spec) + [None] * (leaf.ndim - len(spec))
        used = {a for e in entries if e is not None
                for a in (e if isinstance(e, tuple) else (e,))}
        if dp_axis in used:
            return spec
        for ax in range(leaf.ndim):
            if entries[ax] is None and leaf.shape[ax] % dp == 0:
                entries[ax] = dp_axis
                return P(*entries)
        return spec

    return jax.tree.map(upgrade, specs, shapes, is_leaf=_is_spec)


def zero1_init(optimizer: optax.GradientTransformation, params: PyTree,
               mesh: Mesh, param_specs: PyTree,
               dp_axis: str = "dp",
               opt_specs: Optional[PyTree] = None) -> PyTree:
    """`optimizer.init(params)` with the state created directly in its
    ZeRO-1 (dp-sharded) layout — the replicated state never materializes,
    which is the point for models whose Adam moments don't fit one chip.
    Pair with `build_sharded_train_step(..., zero1=True, params=params)`;
    when you already hold the specs (to share with the step's
    `zero1_specs=`), pass them as `opt_specs` to skip re-derivation.
    """
    if opt_specs is None:
        opt_specs = zero1_opt_specs(optimizer, params, mesh, param_specs,
                                    dp_axis=dp_axis)
    shardings = make_param_shardings(mesh, opt_specs)
    return jax.jit(optimizer.init, out_shardings=shardings)(params)


def fsdp_init(optimizer: optax.GradientTransformation, params: PyTree,
              mesh: Mesh, fsdp_specs: PyTree) -> PyTree:
    """`optimizer.init(params)` with the state born following the FSDP
    params' layout (`opt_state_specs` over the fsdp specs) — the
    one-line companion to `fsdp_param_specs`, so the born-sharded init
    recipe lives here rather than at every call site."""
    o_specs = opt_state_specs(optimizer, params, fsdp_specs)
    shardings = make_param_shardings(mesh, o_specs)
    return jax.jit(optimizer.init, out_shardings=shardings)(params)


def fsdp_param_specs(params: PyTree, mesh: Mesh,
                     base_specs: Optional[PyTree] = None,
                     dp_axis: str = "dp",
                     min_shard_elems: int = 1024) -> PyTree:
    """FSDP (ZeRO-3-style) PartitionSpecs: parameters themselves sharded
    over the dp axis.

    Where ZeRO-1 shards only the optimizer state, FSDP also stores 1/dp
    of every parameter per replica; XLA's SPMD partitioner inserts the
    per-layer all-gathers in forward/backward and keeps gradients in
    reduce-scattered form — the scaling-book FSDP recipe, expressed
    purely as sharding specs (no wrapper modules, no hand-written
    gathers).  Per-step wire cost is ~1.5x a ring all-reduce (two
    param gathers + one grad scatter vs rs+ag) in exchange for
    params+grads+moments all dropping to 1/dp per chip.

    `base_specs` (default all-replicated) lets FSDP compose with TP:
    pass `models.transformer.param_specs(cfg)` and each leaf gains the
    dp axis on a dimension TP left free.  Tiny leaves (biases, norm
    scales, < `min_shard_elems`) stay replicated — gathering them would
    cost more in collective latency than the bytes saved.  Use with
    `build_sharded_train_step(loss_fn, opt, mesh, fsdp_specs)` +
    `opt_state_specs`/`init_sharded` so the optimizer state follows the
    params' layout.
    """
    _check_axis(mesh, dp_axis, "fsdp")
    if base_specs is None:
        base_specs = jax.tree.map(lambda _: P(), params)
    # _shard_free_axis only reads .ndim/.size/.shape — param arrays (or
    # eval_shape structs) provide those directly.
    return _shard_free_axis(base_specs, params, mesh, dp_axis,
                            min_shard_elems)


def build_sharded_train_step(
    loss_fn: Callable[[PyTree, Any], jax.Array],
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    param_specs: PyTree,
    batch_spec: PyTree = P("dp"),
    donate: bool = True,
    zero1: bool = False,
    params: Optional[PyTree] = None,
    zero1_axis: str = "dp",
    zero1_specs: Optional[PyTree] = None,
) -> Callable:
    """jitted `step(params, opt_state, batch) -> (params, opt_state, loss)`
    under GSPMD sharding.  Gradient communication (dp psum, tp collectives)
    is derived by XLA from the in/out shardings — the whole reference
    pipeline (SURVEY §3.2) becomes compiler-inserted collectives fused with
    backward compute.

    `zero1=True` shards the optimizer state over `zero1_axis` (see
    `zero1_opt_specs`).  Deriving those specs needs the concrete param
    shapes, so pass `params` too (the tree you will train; only its
    shapes/structure are read here) — or pass a precomputed
    `zero1_specs` tree to skip the derivation.  Create the state with
    `zero1_init(optimizer, params, mesh, param_specs)` so it is born in
    the sharded layout — a committed replicated state from a bare
    `optimizer.init` would be rejected by the jit's in_shardings.
    """
    p_shardings = make_param_shardings(mesh, param_specs)

    def _step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    donate_argnums = (0, 1) if donate else ()
    batch_shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), batch_spec, is_leaf=_is_spec)

    o_shardings = None
    if zero1:
        if zero1_specs is None:
            if params is None:
                raise TypeError(
                    "zero1=True derives opt-state shardings from the "
                    "param shapes — pass params=<your param tree> "
                    "(shapes/structure only are read), or a precomputed "
                    "zero1_specs=zero1_opt_specs(...)")
            zero1_specs = zero1_opt_specs(optimizer, params, mesh,
                                          param_specs, dp_axis=zero1_axis)
        o_shardings = make_param_shardings(mesh, zero1_specs)

    return jax.jit(
        _step,
        in_shardings=(p_shardings, o_shardings, batch_shardings),
        out_shardings=(p_shardings, o_shardings, NamedSharding(mesh, P())),
        donate_argnums=donate_argnums)


def init_sharded(init_fn: Callable[[], PyTree], mesh: Mesh,
                 specs: PyTree) -> PyTree:
    """Run `init_fn` under jit with output shardings so large params are
    created directly on-device in their final layout (no host staging)."""
    shardings = make_param_shardings(mesh, specs)
    return jax.jit(init_fn, out_shardings=shardings)()
