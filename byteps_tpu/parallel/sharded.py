"""GSPMD sharded training: the pjit/jit + NamedSharding path.

The reference has exactly one parallelism strategy — DP with hand-built
communication (SURVEY §2.6).  On TPU the idiomatic generalisation is to
annotate parameter and batch shardings over a named mesh and let XLA insert
the collectives: DP gradient reduction becomes the psum GSPMD derives from a
dp-sharded batch against replicated params; TP comes from Megatron-style
column/row PartitionSpecs on the weights (models/transformer.param_specs);
SP shards the sequence dimension.  This module packages that recipe.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PyTree = Any


def _is_spec(x):
    return isinstance(x, P)


def make_param_shardings(mesh: Mesh, specs: PyTree) -> PyTree:
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=_is_spec)


def shard_params(params: PyTree, mesh: Mesh, specs: PyTree) -> PyTree:
    """Place a param pytree onto the mesh under `specs` (PartitionSpec
    tree with the same structure)."""
    shardings = make_param_shardings(mesh, specs)
    return jax.tree.map(jax.device_put, params, shardings)


def opt_state_specs(optimizer: optax.GradientTransformation, params: PyTree,
                    specs: PyTree) -> PyTree:
    """Derive PartitionSpecs for the optimizer state.

    Optimizer state trees (adam mu/nu, momentum buffers) embed copies of the
    params tree, so each state leaf is matched to its param by PATH SUFFIX
    — e.g. state path (..., 'mu', 'layers', 'wq') matches param path
    ('layers', 'wq').  Shape matching alone is ambiguous (wq and wo share a
    shape but not a layout).  Unmatched leaves (step counters, scalars)
    replicate."""
    from jax.tree_util import tree_flatten_with_path

    def key_id(k):
        return getattr(k, "key", getattr(k, "name", getattr(k, "idx", None)))

    param_paths, _ = tree_flatten_with_path(params)
    spec_leaves = jax.tree.leaves(specs, is_leaf=_is_spec)
    by_path = {tuple(key_id(k) for k in path): (leaf.shape, spec)
               for (path, leaf), spec in zip(param_paths, spec_leaves)}

    state_shape = jax.eval_shape(optimizer.init, params)
    state_paths, treedef = tree_flatten_with_path(state_shape)
    out = []
    for path, leaf in state_paths:
        ids = tuple(key_id(k) for k in path)
        spec = P()
        for start in range(len(ids)):
            hit = by_path.get(ids[start:])
            if hit is not None and hit[0] == leaf.shape:
                spec = hit[1]
                break
        out.append(spec)
    return jax.tree.unflatten(treedef, out)


def build_sharded_train_step(
    loss_fn: Callable[[PyTree, Any], jax.Array],
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    param_specs: PyTree,
    batch_spec: PyTree = P("dp"),
    donate: bool = True,
) -> Callable:
    """jitted `step(params, opt_state, batch) -> (params, opt_state, loss)`
    under GSPMD sharding.  Gradient communication (dp psum, tp collectives)
    is derived by XLA from the in/out shardings — the whole reference
    pipeline (SURVEY §3.2) becomes compiler-inserted collectives fused with
    backward compute.
    """
    p_shardings = make_param_shardings(mesh, param_specs)

    def _step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    donate_argnums = (0, 1) if donate else ()
    batch_shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), batch_spec, is_leaf=_is_spec)

    return jax.jit(
        _step,
        in_shardings=(p_shardings, None, batch_shardings),
        out_shardings=(p_shardings, None, NamedSharding(mesh, P())),
        donate_argnums=donate_argnums)


def init_sharded(init_fn: Callable[[], PyTree], mesh: Mesh,
                 specs: PyTree) -> PyTree:
    """Run `init_fn` under jit with output shardings so large params are
    created directly on-device in their final layout (no host staging)."""
    shardings = make_param_shardings(mesh, specs)
    return jax.jit(init_fn, out_shardings=shardings)()
