"""Asynchronous PS training: workers push weight deltas, no round barrier.

The reference's BYTEPS_ENABLE_ASYNC mode (reference: torch/__init__.py
step() under `_enable_async` at 186-214, server.cc:319-323): each worker
runs its local optimizer step, pushes the resulting weight *delta*
(w_new - w_old), and the server applies `store += delta` immediately —
no synchronization across workers.  The pull returns the server's current
global weights, which replace the worker's local params.  Convergence is
the classic async-SGD contract: workers may compute on slightly stale
weights.

TPU-native shape: the functional equivalent of the reference's in-place
`p.data.sub_(old); push_pull(p)` is an explicit trainer object that flattens
the param pytree once, tracks the last pulled global weights, and exposes
one `step(updated_params)` call.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

PyTree = Any


class AsyncPSTrainer:
    """Weight-delta async training against an async-mode PS server tier.

    Usage (server must run with BYTEPS_ENABLE_ASYNC=1):

        trainer = AsyncPSTrainer(session, params, name="model")
        for batch in data:
            updated = local_sgd_step(trainer.params, batch)  # any local opt
            trainer.step(updated)          # push delta, pull global weights
            # trainer.params now holds the global view
    """

    def __init__(self, session, params: PyTree, name: str = "async_param",
                 declared_key: Optional[int] = None):
        import jax

        if getattr(session, "server_async", True) is False:
            raise RuntimeError(
                "AsyncPSTrainer requires servers running with "
                "BYTEPS_ENABLE_ASYNC=1; against a sync server the weight-"
                "delta protocol would silently train on deltas")
        self._session = session
        self._treedef = jax.tree.structure(params)
        leaves = jax.tree.leaves(params)
        self._shapes = [np.shape(l) for l in leaves]
        self._sizes = [int(np.size(l)) for l in leaves]
        self._dtypes = [np.asarray(l).dtype for l in leaves]
        if declared_key is None:
            from ..core.native import get_core
            declared_key = get_core().declare_tensor(f"AsyncParam.{name}")
        self._key = declared_key
        self._flat = self._flatten(params)
        # Seed the server store with the initial weights.  DT_SEED applies
        # only if the key has never been pushed — a late-joining or
        # rejoining worker adopts the live global weights from the pull
        # instead of resetting them (the analog of the reference's init
        # push populating the store before deltas flow,
        # reference: operations.cc:369-378).
        h = session.push_pull_async(self._key, self._flat, seed=True)
        self._flat = h.wait().astype(np.float32)

    def _flatten(self, params: PyTree) -> np.ndarray:
        import jax

        leaves = jax.tree.leaves(params)
        return np.concatenate(
            [np.asarray(l, np.float32).ravel() for l in leaves])

    def _unflatten(self, flat: np.ndarray) -> PyTree:
        import jax

        out, off = [], 0
        for shape, size, dtype in zip(self._shapes, self._sizes,
                                      self._dtypes):
            out.append(flat[off:off + size].reshape(shape).astype(dtype))
            off += size
        return jax.tree.unflatten(self._treedef, out)

    @property
    def params(self) -> PyTree:
        """The latest pulled global weights, as the original pytree."""
        return self._unflatten(self._flat)

    def step(self, updated_params: PyTree) -> PyTree:
        """Push (updated - last_global) delta; pull and adopt global weights."""
        new_flat = self._flatten(updated_params)
        delta = new_flat - self._flat
        self._flat = self._session.push_pull(self._key, delta).astype(
            np.float32)
        return self.params
