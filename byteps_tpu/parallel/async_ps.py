"""Asynchronous PS training: workers push weight deltas, no round barrier.

The reference's BYTEPS_ENABLE_ASYNC mode (reference: torch/__init__.py
step() under `_enable_async` at 186-214, server.cc:319-323): each worker
runs its local optimizer step, pushes the resulting weight *delta*
(w_new - w_old), and the server applies `store += delta` immediately —
no synchronization across workers.  The pull returns the server's current
global weights, which replace the worker's local params.  Convergence is
the classic async-SGD contract: workers may compute on slightly stale
weights.

TPU-native shape: the functional equivalent of the reference's in-place
`p.data.sub_(old); push_pull(p)` is an explicit trainer object that flattens
the param pytree once, tracks the last adopted global weights, and exposes
one `step(updated_params)` call.

Wire layout: with fusion enabled (BYTEPS_TPU_FUSION_BYTES > 0, the
default) the delta no longer rides one monolithic key — the fusion
planner (common/fusion.py) packs small param leaves into size-capped
buckets in reverse backprop order and leaves large params on their own
keys, each dispatched at its backprop-position priority through
PSSession.push_pull_group.  Last-layer buckets hit the wire first and the
session can overlap their round-trips instead of serializing one giant
transfer; BYTEPS_TPU_FUSION_BYTES=0 (or a session without
push_pull_group) restores the single flat vector.

Pipelining: by default the trainer double-buffers — `step()` dispatches the
new delta and waits only for the *previous* round, never its own, so each
round's network round-trip overlaps the local compute of the NEXT step
instead of serializing after it (the eager analog of the reference's
communication/compute overlap: core_loops.cc pipeline,
torch/cross_barrier.py).  Because consecutive rounds share partition keys,
the session's sequential-use guard orders round k+1's wire dispatch after
round k's pull — the overlap is round-trip-against-compute, not two
simultaneous wire transfers.  Each pushed delta is the pure local optimizer
movement, so pipelining never double-counts: the adopted view is
`global_after_previous_round + own_in_flight_movement`.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

PyTree = Any


class AsyncPSTrainer:
    """Weight-delta async training against an async-mode PS server tier.

    Usage (server must run with BYTEPS_ENABLE_ASYNC=1):

        trainer = AsyncPSTrainer(session, params, name="model")
        for batch in data:
            updated = local_sgd_step(trainer.params, batch)  # any local opt
            trainer.step(updated)          # push delta, adopt global view
            # trainer.params now holds the (possibly 1-round-stale) view
        final = trainer.finalize()         # drain in-flight, pure global

    `pipeline=False` restores the fully synchronous push→wait→adopt cycle
    (one round in flight, zero staleness relative to the server).
    """

    def __init__(self, session, params: PyTree, name: str = "async_param",
                 declared_key: Optional[int] = None, pipeline: bool = True,
                 fusion_bytes: Optional[int] = None, hierarchy=None):
        import jax

        if getattr(session, "server_async", True) is False:
            raise RuntimeError(
                "AsyncPSTrainer requires servers running with "
                "BYTEPS_ENABLE_ASYNC=1; against a sync server the weight-"
                "delta protocol would silently train on deltas")
        self._session = session
        self._pipeline = pipeline
        # Hierarchical reduction (BYTEPS_TPU_HIERARCHY=1, parallel/
        # hierarchy.py): slice-reduce each round's delta in-graph, only
        # the slice leader rides the wire, the pulled global weights
        # broadcast back.  None reads the env opt-in; pass an explicit
        # HierarchicalReducer to share a custom topology.
        if hierarchy is None:
            from .hierarchy import maybe_reducer
            hierarchy = maybe_reducer(session)
        self._hier = hierarchy
        self._treedef = jax.tree.structure(params)
        leaves = jax.tree.leaves(params)
        self._shapes = [np.shape(l) for l in leaves]
        self._sizes = [int(np.size(l)) for l in leaves]
        self._dtypes = [np.asarray(l).dtype for l in leaves]
        if declared_key is None:
            from ..core.native import get_core
            declared_key = get_core().declare_tensor(f"AsyncParam.{name}")
        self._key = declared_key
        self._chunks = self._plan_chunks(name, fusion_bytes)
        self._flat = self._flatten(params)
        # Outstanding round: (handle, in-flight movement) — at most one.
        self._pending = None
        # Seed the server store with the initial weights.  DT_SEED applies
        # only if the key has never been pushed — a late-joining or
        # rejoining worker adopts the live global weights from the pull
        # instead of resetting them (the analog of the reference's init
        # push populating the store before deltas flow,
        # reference: operations.cc:369-378).
        self._flat = self._dispatch(self._flat, seed=True).wait() \
            .astype(np.float32)

    def _plan_chunks(self, name: str, fusion_bytes: Optional[int]):
        """[(declared_key, flat_ranges, priority)] in priority-descending
        dispatch order, or None for the single-key layout.

        Routes the flat f32 param vector through the fusion planner:
        small leaves pack into buckets (reverse backprop order, bucket
        priority = max member position), large leaves go solo at their
        own position.  Chunk keys are derived from the deterministic
        bucket tags, so every worker — and a restarted worker after
        re-declare — maps the same params to the same wire keys.
        """
        from ..common import fusion
        from ..common.config import get_config
        from ..core.native import get_core

        fb = (get_config().fusion_bytes if fusion_bytes is None
              else int(fusion_bytes))
        if fb <= 0 or len(self._sizes) < 2 \
                or not hasattr(self._session, "push_pull_group"):
            return None
        plan = fusion.plan_buckets(
            tuple((i, n, "float32", 4) for i, n in enumerate(self._sizes)),
            fb)
        plan.record_use()
        offs = np.concatenate([[0], np.cumsum(self._sizes)]).astype(np.int64)
        core = get_core()
        # Chunk names incorporate the trainer's resolved key so trainers
        # kept distinct by an explicit declared_key (same `name`) stay
        # distinct on the wire, exactly as their single-key layouts would.
        base = f"AsyncParam.{name}.k{self._key}"
        chunks = []
        for b in plan.buckets:
            ranges = [(int(offs[li]), int(offs[li]) + n)
                      for li, n in b.members]
            chunks.append((core.declare_tensor(f"{base}.{b.tag}"),
                           ranges, b.priority))
        for li, prio in plan.solo:
            chunks.append((
                core.declare_tensor(f"{base}.leaf{li}"),
                [(int(offs[li]), int(offs[li + 1]))], prio))
        if len(chunks) < 2:
            return None
        chunks.sort(key=lambda c: -c[2])
        return chunks

    def _dispatch(self, flat: np.ndarray, seed: bool = False):
        """Push one round's flat payload; returns an object whose
        .wait(timeout) yields the assembled global flat vector."""
        if self._hier is not None:
            # Hierarchical round: the slice's deltas sum in-graph, the
            # LEADER runs the wire leg below (same chunked layout), and
            # followers' handles resolve from the leader's broadcast.
            # Seeds skip the reduce — the initial weights are identical
            # on every member, and summing S copies would corrupt the
            # store (hierarchy.dispatch_round owns that law).
            return self._hier.dispatch_round(
                self._key, flat, seed=seed,
                leader_dispatch=lambda reduced: self._wire_dispatch(
                    reduced, seed))
        return self._wire_dispatch(flat, seed)

    def _wire_dispatch(self, flat: np.ndarray, seed: bool = False):
        if self._chunks is None:
            return self._session.push_pull_async(self._key, flat, seed=seed)
        items = [(key, _gather(flat, ranges), prio)
                 for key, ranges, prio in self._chunks]
        handles = self._session.push_pull_group(items, seed=seed)
        return _GroupRoundHandle(handles, self._chunks, len(flat))

    def _flatten(self, params: PyTree) -> np.ndarray:
        import jax

        leaves = jax.tree.leaves(params)
        return np.concatenate(
            [np.asarray(l, np.float32).ravel() for l in leaves])

    def _unflatten(self, flat: np.ndarray) -> PyTree:
        import jax

        out, off = [], 0
        for shape, size, dtype in zip(self._shapes, self._sizes,
                                      self._dtypes):
            out.append(flat[off:off + size].reshape(shape).astype(dtype))
            off += size
        return jax.tree.unflatten(self._treedef, out)

    @property
    def params(self) -> PyTree:
        """The current local view (last adopted global + own in-flight
        movement), as the original pytree."""
        return self._unflatten(self._flat)

    def step(self, updated_params: PyTree) -> PyTree:
        """Push the local movement (updated - current view) as a delta.

        Pipelined (default): dispatch the new delta, then wait for the
        PREVIOUS round's pull — which had the whole local compute step that
        produced `updated_params` to complete, so a step blocks on the
        network only for whatever round-trip time compute didn't already
        cover.  The adopted view is `global_after_prev +
        in_flight_movement`; the in-flight movement is folded in again when
        its own round is adopted next step, and the server has it already,
        so nothing is counted twice.
        """
        new_flat = self._flatten(updated_params)
        delta = new_flat - self._flat
        handle = self._dispatch(delta)
        if not self._pipeline:
            self._flat = handle.wait().astype(np.float32)
            return self.params
        prev, self._pending = self._pending, (handle, delta)
        if prev is not None:
            prev_handle, _prev_delta = prev
            g = prev_handle.wait().astype(np.float32)
            # g reflects the server *after* our previous round; our newest
            # movement (delta) is still in flight, so keep it locally.
            self._flat = g + delta
        else:
            self._flat = new_flat
        return self.params

    def finalize(self, timeout: Optional[float] = 300.0) -> PyTree:
        """Drain the in-flight round and adopt the pure global weights."""
        if self._pending is not None:
            handle, _delta = self._pending
            self._pending = None
            self._flat = handle.wait(timeout).astype(np.float32)
        return self.params

    # -- elastic input-pipeline re-sharding (docs/elasticity.md) ----------
    def data_shard(self, membership: Optional[dict] = None) -> tuple:
        """``(shard_index, shard_count)`` for this worker's input
        pipeline.  The index is this worker's position among the SORTED
        alive ids, so shards stay dense after a join or eviction even
        when worker ids have gaps; with no membership view (or a fixed
        epoch-0 job) it is the launch ``(worker_id, num_worker)``."""
        wid = int(getattr(self._session, "worker_id", 0))
        if membership is None or int(membership.get("epoch", 0)) == 0:
            from ..common.config import get_config
            return wid, max(1, int(get_config().num_worker))
        alive = sorted(int(w) for w in membership.get("alive", ()))
        if not alive:
            return 0, 1
        if wid not in alive:
            # Evicted self: the value is moot (this worker's pushes no
            # longer count) but must stay well-formed for shutdown paths.
            return 0, len(alive)
        return alive.index(wid), len(alive)

    def membership_callback(self, on_reshard):
        """A ``callback(membership)`` for :func:`bps.on_membership_change`
        that re-derives this worker's data shard on every epoch change
        and calls ``on_reshard(shard_index, shard_count, membership)``
        exactly when the shard actually moved — epoch bumps that leave
        the shard unchanged (e.g. an unrelated slice departing) stay
        quiet, so the input pipeline never reshuffles needlessly."""
        state = {"shard": self.data_shard()}

        def _cb(membership):
            shard = self.data_shard(membership)
            if shard != state["shard"]:
                state["shard"] = shard
                on_reshard(shard[0], shard[1], membership)

        return _cb

    def enable_reshard(self, on_reshard, poll_s: Optional[float] = None):
        """Wire :func:`bps.on_membership_change` into this trainer so the
        input pipeline re-shards itself on worker join/evict (ROADMAP
        autoscaling item (b)).

        ``on_reshard(shard_index, shard_count, membership)`` fires when —
        and only when — this worker's dense shard assignment changes;
        size()/rank() already follow the new epoch by the time it runs,
        so the handler can rebuild its data iterator directly.  Returns
        the registered callback (also usable standalone when the caller
        drives its own membership polling).  Requires an initialized PS
        session (``bps.init()``) — the api poller owns the CMD_MEMBERS
        traffic."""
        from ..common import api
        cb = self.membership_callback(on_reshard)
        api.on_membership_change(cb, poll_s)
        return cb


def _gather(flat: np.ndarray, ranges) -> np.ndarray:
    """Concatenate the flat-vector slices a chunk covers (a zero-copy view
    for the common single-run case)."""
    if len(ranges) == 1:
        a, b = ranges[0]
        return flat[a:b]
    return np.concatenate([flat[a:b] for a, b in ranges])


class _GroupRoundHandle:
    """Completion handle over one round's chunked dispatch: waits every
    chunk and scatters the pulled global values back into one flat f32
    vector (the single-key handle's .wait() contract)."""

    def __init__(self, handles, chunks, n: int):
        self._handles = handles
        self._chunks = chunks
        self._n = n

    def done(self) -> bool:
        return all(h.done() for h in self._handles)

    def wait(self, timeout: Optional[float] = 300.0) -> np.ndarray:
        import time
        # One deadline for the WHOLE round (the single-key contract), not
        # per chunk — num_chunks x timeout against a hung server would
        # stretch a 30s budget into minutes.
        deadline = None if timeout is None else time.monotonic() + timeout
        out = np.empty(self._n, np.float32)
        for h, (_key, ranges, _prio) in zip(self._handles, self._chunks):
            left = (None if deadline is None
                    else max(0.001, deadline - time.monotonic()))
            got = np.asarray(h.wait(left), np.float32).ravel()
            off = 0
            for a, b in ranges:
                out[a:b] = got[off:off + (b - a)]
                off += b - a
        return out
