"""Cross-barrier pipelining: overlap communication/update with next-step
compute.

The reference implements ByteScheduler-style cross-barrier execution with a
poller thread, per-parameter optimizers and per-parameter locks that let
the next iteration's forward start before all push_pulls finish
(reference: torch/cross_barrier.py:28-231, docs/cross-barrier.md).

On TPU the barrier being removed is the HOST-side sync: inside one jitted
step XLA's latency-hiding scheduler already overlaps bucket collectives
with backward compute (the in-graph analog of per-parameter locks), so the
remaining win is keeping the device queue full across steps.  JAX's async
dispatch gives exactly that — as long as the host never blocks on a step's
results.  `CrossBarrierDriver` packages the discipline:

  - steps are dispatched eagerly; the host loop runs ahead of the device,
  - `max_in_flight` bounds the run-ahead (the reference's credit system,
    scheduled_queue.cc:136-139, in step units),
  - losses are fetched asynchronously and only synchronized when read.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Iterable, Iterator, Optional, Tuple

import jax

PyTree = Any


class CrossBarrierDriver:
    """Run a jitted train step without host-side barriers.

    step(params, opt_state, batch) -> (params, opt_state, loss)

    Usage:
        drv = CrossBarrierDriver(step, params, opt_state, max_in_flight=2)
        for batch in data:
            drv.submit(batch)        # returns immediately
        params, opt_state = drv.finish()
        losses = drv.losses()        # floats, synchronized
    """

    def __init__(self, step: Callable, params: PyTree, opt_state: PyTree,
                 max_in_flight: int = 2):
        if max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1")
        self._step = step
        self._params = params
        self._opt_state = opt_state
        self._max = max_in_flight
        self._pending: collections.deque = collections.deque()
        self._losses: list = []

    def submit(self, batch: PyTree) -> None:
        """Dispatch one training step; blocks only when more than
        `max_in_flight` steps' losses are unresolved (the credit gate)."""
        self._params, self._opt_state, loss = self._step(
            self._params, self._opt_state, batch)
        self._pending.append(loss)
        while len(self._pending) > self._max:
            # Resolving the oldest loss waits for that step's completion —
            # bounded run-ahead, like returning communication credits.
            self._losses.append(float(self._pending.popleft()))

    def finish(self) -> Tuple[PyTree, PyTree]:
        """Drain the queue; returns (params, opt_state) fully materialized."""
        while self._pending:
            self._losses.append(float(self._pending.popleft()))
        jax.block_until_ready(self._params)
        return self._params, self._opt_state

    def losses(self) -> list:
        return list(self._losses)

    @property
    def state(self) -> Tuple[PyTree, PyTree]:
        """Current (possibly still-in-flight) params/opt_state."""
        return self._params, self._opt_state


def run_cross_barrier(step: Callable, params: PyTree, opt_state: PyTree,
                      batches: Iterable, max_in_flight: int = 2
                      ) -> Tuple[PyTree, PyTree, list]:
    """Convenience wrapper: train over `batches` with cross-barrier
    pipelining; returns (params, opt_state, losses)."""
    drv = CrossBarrierDriver(step, params, opt_state, max_in_flight)
    for b in batches:
        drv.submit(b)
    params, opt_state = drv.finish()
    return params, opt_state, drv.losses()
