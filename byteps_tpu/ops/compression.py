"""Framework-level ("intra-node") compression: the `Compression` enum.

Mirrors the reference's two-level compression design
(reference: docs/gradient-compression.md:11-17): this module is level 1 — the
Horovod-style fp16 cast applied before communication and undone after
(reference: byteps/torch/compression.py equivalent, byteps/tensorflow/
__init__.py:66-81).  Level 2 (the inter-node onebit/topk/randomk/dithering
compressors with error-feedback and momentum) lives in
byteps_tpu.ops.compressor as shape-static jnp/XLA ops (vectorized packing
via reshape+dot — XLA fuses them into the surrounding collectives; no
hand-written Pallas kernels are needed at these sizes).

On TPU the natural wire dtype is bfloat16 (no loss of exponent range), so
`Compression.fp16` maps to bf16 by default; `Compression.f16` forces IEEE
half for bit-parity with the reference.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class Compressor:
    """A bidirectional dtype cast around communication."""

    def compress(self, tensor: jax.Array):
        """Returns (compressed_tensor, ctx) — ctx is whatever decompress needs."""
        raise NotImplementedError

    def decompress(self, tensor: jax.Array, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    def compress(self, tensor):
        return tensor, None

    def decompress(self, tensor, ctx):
        return tensor


class CastCompressor(Compressor):
    def __init__(self, wire_dtype):
        self.wire_dtype = jnp.dtype(wire_dtype)

    def compress(self, tensor):
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            return tensor.astype(self.wire_dtype), tensor.dtype
        return tensor, None

    def decompress(self, tensor, ctx):
        return tensor if ctx is None else tensor.astype(ctx)


class Compression:
    """Namespace matching the reference API: bps.Compression.fp16 etc."""

    none = NoneCompressor()
    fp16 = CastCompressor(jnp.bfloat16)   # TPU-native half: bf16
    f16 = CastCompressor(jnp.float16)     # strict IEEE half
    bf16 = CastCompressor(jnp.bfloat16)
