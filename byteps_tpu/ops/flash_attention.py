"""Flash attention as a Pallas TPU kernel (forward + backward).

The flagship transformer's dense attention materializes the [S, S] logits
in HBM per layer (models/transformer.py dense_attention) — the classic
memory-bound hot spot.  This kernel computes attention blockwise with an
online softmax so nothing bigger than a (block_q, block_k) tile ever
leaves VMEM, and the backward recomputes probabilities blockwise from the
saved log-sum-exp instead of storing them.

This is the compute-path counterpart of the reference's CUDA-side
optimizations: the reference framework leaves model compute to
torch/cudnn (no attention kernels of its own); a TPU-native framework
owns its hot ops, so the kernel lives here (pallas guide: grid/BlockSpec
tiling onto the MXU, f32 accumulation, custom-VJP pattern).

Layout: q, k, v are [BH, S, D] (batch*heads folded into the grid's first
axis).  S must divide by the block sizes and D should be a multiple of 8
(128 ideal for the MXU lane dimension; BERT-class D=64 works).  Callers
that don't satisfy the constraints should fall back to dense attention —
`models.transformer.flash_attention_fn` does exactly that.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = float("-inf")


def _use_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def _causal_mask(s, qi, kb, block_q, block_k):
    """Mask logits where key position > query position (global indices)."""
    rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + qi * block_q
    cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + kb * block_k
    return jnp.where(rows >= cols, s, NEG_INF)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal,
                block_q, block_k, seq_len):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale          # (bq, d)
    bq, d = q.shape

    num_kb = seq_len // block_k
    if causal:
        # Only key blocks whose first row can be visible to this q block.
        num_kb = jnp.minimum(num_kb,
                             ((qi + 1) * block_q + block_k - 1) // block_k)

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bq, bk)
        if causal:
            s = _causal_mask(s, qi, kb, block_q, block_k)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)                           # (bq, bk)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))

    o_ref[0] = (acc / l).astype(o_ref.dtype)
    # log-sum-exp of the scaled logits, saved for the backward recompute.
    # Layout (BH, 1, S): TPU block tiling needs the last two dims to be
    # (1, block) with both either tile-divisible or dim-equal.
    lse_ref[0, 0, :] = (m + jnp.log(l))[:, 0]


def _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    bh, s, d = q.shape
    grid = (bh, s // block_q)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_len=s)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, s), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


# ---------------------------------------------------------------------------
# Backward: dq over q blocks; dk/dv over k blocks.  Probabilities are
# recomputed from q,k and the saved lse (the flash-attention backward).
# ---------------------------------------------------------------------------
def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               sm_scale, causal, block_q, block_k, seq_len):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)                     # (bq, d)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0, :][:, None]                      # (bq, 1)
    delta = delta_ref[0, 0, :][:, None]
    bq, d = q.shape

    num_kb = seq_len // block_k
    if causal:
        num_kb = jnp.minimum(num_kb,
                             ((qi + 1) * block_q + block_k - 1) // block_k)

    def body(kb, dq):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = sm_scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, qi, kb, block_q, block_k)
        p = jnp.exp(s - lse)                             # (bq, bk)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + sm_scale * jnp.dot(
            ds, k, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, num_kb, body,
                           jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, sm_scale, causal, block_q, block_k,
                seq_len):
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)                     # (bk, d)
    v = v_ref[0].astype(jnp.float32)
    bk, d = k.shape

    num_qb = seq_len // block_q
    start_qb = 0
    if causal:
        # Query blocks strictly before this key block see none of it.
        start_qb = (ki * block_k) // block_q

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(qb * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, pl.ds(qb * block_q, block_q)][:, None]
        s = sm_scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bq, bk)
        if causal:
            s = _causal_mask(s, qb, ki, block_q, block_k)
        p = jnp.exp(s - lse)
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bk, d)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bq, bk)
        ds = p * (dp - delta)
        dk = dk + sm_scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    z = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(start_qb, num_qb, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(sm_scale, causal, block_q, block_k, interpret, residuals, g):
    q, k, v, o, lse = residuals
    do = g
    bh, s, d = q.shape
    # delta_i = rowsum(dO_i * O_i): tiny elementwise pass, XLA fuses it.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, None, :]                 # (bh, 1, s)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_len=s),
        grid=(bh, s // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_len=s),
        grid=(bh, s // block_k),
        in_specs=[
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, s), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, s), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, sm_scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Blockwise (flash) attention.  q, k, v: [BH, S, D] -> [BH, S, D].

    sm_scale defaults to 1/sqrt(D).  interpret=None auto-selects the
    Pallas interpreter off-TPU so tests run on the CPU mesh.
    """
    out, _ = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k,
                        interpret)
    return out


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    bh, s, d = q.shape
    if s % block_q or s % block_k:
        raise ValueError(
            f"seq_len {s} must divide block_q={block_q}, block_k={block_k}"
            " — use models.transformer.flash_attention_fn for the"
            " auto-fallback to dense attention")
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    out, lse = _fwd(q, k, v, scale, causal, block_q, block_k,
                    _use_interpret(interpret))
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, residuals, g):
    d = residuals[0].shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    return _bwd(scale, causal, block_q, block_k, _use_interpret(interpret),
                residuals, g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
