"""Flash attention as a Pallas TPU kernel (forward + backward).

The flagship transformer's dense attention materializes the [S, S] logits
in HBM per layer (models/transformer.py dense_attention) — the classic
memory-bound hot spot.  This kernel computes attention blockwise with an
online softmax so nothing bigger than a (block_q, block_k) tile of logits
ever exists, and the backward recomputes probabilities blockwise from the
saved log-sum-exp instead of storing them.

Two execution strategies, auto-selected by VMEM footprint:

  - **resident** (short/medium S): K and V live in VMEM for the whole
    kernel; each q block loops over them with `lax.fori_loop`.  K/V are
    fetched from HBM once per (batch*head), which is what makes the
    kernel beat XLA's fused dense attention (measured 1.6x at S=4096 on
    v5e, docs/performance.md).
  - **streaming** (long S): 3D grid with the contraction axis innermost —
    (bh, q_blocks, k_blocks) forward/dq, (bh, k_blocks, q_blocks) dk/dv —
    carrying running statistics in VMEM scratch across the innermost
    iterations (the matmul k-loop pattern).  Per-program VMEM is
    O(block * d) regardless of S, so the kernel keeps compiling at 32k+
    contexts, at the price of re-streaming K/V once per q block.

Causal grids predicate away upper-triangle blocks (`pl.when` in the
streaming path, a shortened `fori_loop` bound in the resident path) so
masked blocks' matmuls never issue.

This is the compute-path counterpart of the reference's CUDA-side
optimizations: the reference leaves model compute to torch/cudnn (no
attention kernels of its own); a TPU-native framework owns its hot ops
(pallas guide: grid/BlockSpec tiling onto the MXU, f32 accumulation,
custom-VJP pattern).

Layout: q, k, v are [BH, S, D] (batch*heads folded into the grid's first
axis).  The block sizes must divide S; D should be a multiple of 8 (128
ideal for the MXU lane).  Callers that don't satisfy the constraints
should fall back to dense attention — `models.transformer.
flash_attention_fn` does exactly that.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float("-inf")

# K+V (resident path) above this many bytes switch to the streaming path;
# ~16MB VMEM/core on current TPUs, leave room for q/o/do tiles + scratch.
RESIDENT_VMEM_BUDGET = 6 * 1024 * 1024


def _use_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def _use_streaming(q, streaming: Optional[bool]) -> bool:
    if streaming is not None:
        return streaming
    _bh, s, d = q.shape
    return 2 * s * d * q.dtype.itemsize > RESIDENT_VMEM_BUDGET


def _causal_mask(s, qi, kb, block_q, block_k):
    """Mask logits where key position > query position (global indices)."""
    rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + qi * block_q
    cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + kb * block_k
    return jnp.where(rows >= cols, s, NEG_INF)


def _block_live(causal, qi, kb, block_q, block_k):
    """Whether any (row, col) in this (q block, k block) pair is visible."""
    if not causal:
        return True
    return (qi + 1) * block_q - 1 >= kb * block_k


def _online_step(q_scaled, k, v, carry, qi, kb, causal, block_q, block_k):
    """One online-softmax accumulation step shared by both forward paths."""
    m, l, acc = carry
    s = jax.lax.dot_general(
        q_scaled, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)              # (bq, bk)
    if causal:
        s = _causal_mask(s, qi, kb, block_q, block_k)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.dot(p, v,
                                    preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def _dq_step(q, k, v, do, lse, delta, sm_scale, qi, kb, causal, block_q,
             block_k):
    s = sm_scale * jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if causal:
        s = _causal_mask(s, qi, kb, block_q, block_k)
    p = jnp.exp(s - lse)                                 # (bq, bk)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    return sm_scale * jnp.dot(ds, k, preferred_element_type=jnp.float32)


def _dkv_step(q, k, v, do, lse, delta, sm_scale, qb, ki, causal, block_q,
              block_k):
    s = sm_scale * jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)              # (bq, bk)
    if causal:
        s = _causal_mask(s, qb, ki, block_q, block_k)
    p = jnp.exp(s - lse)
    dv = jax.lax.dot_general(
        p, do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)              # (bk, d)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    dk = sm_scale * jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return dk, dv


# ---------------------------------------------------------------------------
# Resident path: K/V whole in VMEM; grid (bh, q_blocks); fori_loop over k.
# ---------------------------------------------------------------------------
def _fwd_kernel_res(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale,
                    causal, block_q, block_k, seq_len):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale          # (bq, d)
    bq, d = q.shape
    num_kb = seq_len // block_k
    if causal:
        num_kb = jnp.minimum(num_kb,
                             ((qi + 1) * block_q + block_k - 1) // block_k)

    def body(kb, carry):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        return _online_step(q, k, v, carry, qi, kb, causal, block_q,
                            block_k)

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    # Layout (BH, 1, S): TPU block tiling needs the last two dims to be
    # (1, block) with both tile-divisible or dim-equal.
    lse_ref[0, 0, :] = (m + jnp.log(l))[:, 0]


def _dq_kernel_res(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, sm_scale, causal, block_q, block_k, seq_len):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0, :][:, None]
    delta = delta_ref[0, 0, :][:, None]
    bq, d = q.shape
    num_kb = seq_len // block_k
    if causal:
        num_kb = jnp.minimum(num_kb,
                             ((qi + 1) * block_q + block_k - 1) // block_k)

    def body(kb, dq):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        return dq + _dq_step(q, k, v, do, lse, delta, sm_scale, qi, kb,
                             causal, block_q, block_k)

    dq = jax.lax.fori_loop(0, num_kb, body,
                           jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel_res(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, sm_scale, causal, block_q, block_k,
                    seq_len):
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)                     # (bk, d)
    v = v_ref[0].astype(jnp.float32)
    bk, d = k.shape
    num_qb = seq_len // block_q
    start_qb = (ki * block_k) // block_q if causal else 0

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(qb * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, pl.ds(qb * block_q, block_q)][:, None]
        dk_i, dv_i = _dkv_step(q, k, v, do, lse, delta, sm_scale, qb, ki,
                               causal, block_q, block_k)
        return dk + dk_i, dv + dv_i

    z = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(start_qb, num_qb, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# Streaming path: 3D grid, contraction axis innermost, scratch carries.
# ---------------------------------------------------------------------------
def _fwd_kernel_str(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                    acc_scr, *, sm_scale, causal, block_q, block_k):
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    last_kb = pl.num_programs(2) - 1

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(_block_live(causal, qi, kb, block_q, block_k))
    def _step():
        q = q_ref[0].astype(jnp.float32) * sm_scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        m, l, acc = _online_step(q, k, v,
                                 (m_scr[:], l_scr[:], acc_scr[:]),
                                 qi, kb, causal, block_q, block_k)
        m_scr[:], l_scr[:], acc_scr[:] = m, l, acc

    @pl.when(kb == last_kb)
    def _finish():
        l = l_scr[:]
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0, 0, :] = (m_scr[:] + jnp.log(l))[:, 0]


def _dq_kernel_str(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, sm_scale, causal, block_q, block_k):
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    last_kb = pl.num_programs(2) - 1

    @pl.when(kb == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(_block_live(causal, qi, kb, block_q, block_k))
    def _step():
        dq_scr[:] = dq_scr[:] + _dq_step(
            q_ref[0].astype(jnp.float32),
            k_ref[0].astype(jnp.float32),
            v_ref[0].astype(jnp.float32),
            do_ref[0].astype(jnp.float32),
            lse_ref[0, 0, :][:, None], delta_ref[0, 0, :][:, None],
            sm_scale, qi, kb, causal, block_q, block_k)

    @pl.when(kb == last_kb)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel_str(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, sm_scale, causal,
                    block_q, block_k):
    ki = pl.program_id(1)
    qb = pl.program_id(2)
    last_qb = pl.num_programs(2) - 1

    @pl.when(qb == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(_block_live(causal, qb, ki, block_q, block_k))
    def _step():
        dk_i, dv_i = _dkv_step(
            q_ref[0].astype(jnp.float32),
            k_ref[0].astype(jnp.float32),
            v_ref[0].astype(jnp.float32),
            do_ref[0].astype(jnp.float32),
            lse_ref[0, 0, :][:, None], delta_ref[0, 0, :][:, None],
            sm_scale, qb, ki, causal, block_q, block_k)
        dk_scr[:] = dk_scr[:] + dk_i
        dv_scr[:] = dv_scr[:] + dv_i

    @pl.when(qb == last_qb)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call builders
# ---------------------------------------------------------------------------
def _q_spec(block_q, d):
    return pl.BlockSpec((1, block_q, d), lambda b, i, *_: (b, i, 0))


def _lse_spec(block_q):
    return pl.BlockSpec((1, 1, block_q), lambda b, i, *_: (b, 0, i))


def _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret, streaming):
    bh, s, d = q.shape
    out_shape = [jax.ShapeDtypeStruct((bh, s, d), q.dtype),
                 jax.ShapeDtypeStruct((bh, 1, s), jnp.float32)]
    if streaming:
        return pl.pallas_call(
            functools.partial(_fwd_kernel_str, sm_scale=sm_scale,
                              causal=causal, block_q=block_q,
                              block_k=block_k),
            grid=(bh, s // block_q, s // block_k),
            in_specs=[
                _q_spec(block_q, d),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            ],
            out_specs=[_q_spec(block_q, d), _lse_spec(block_q)],
            out_shape=out_shape,
            scratch_shapes=[
                pltpu.VMEM((block_q, 1), jnp.float32),   # running max m
                pltpu.VMEM((block_q, 1), jnp.float32),   # running sum l
                pltpu.VMEM((block_q, d), jnp.float32),   # accumulator
            ],
            interpret=interpret,
        )(q, k, v)
    kv_spec = pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0))
    return pl.pallas_call(
        functools.partial(_fwd_kernel_res, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_len=s),
        grid=(bh, s // block_q),
        in_specs=[_q_spec(block_q, d), kv_spec, kv_spec],
        out_specs=[_q_spec(block_q, d), _lse_spec(block_q)],
        out_shape=out_shape,
        interpret=interpret,
    )(q, k, v)


def _bwd(sm_scale, causal, block_q, block_k, interpret, streaming,
         residuals, g):
    q, k, v, o, lse = residuals
    do = g
    bh, s, d = q.shape
    # delta_i = rowsum(dO_i * O_i): tiny elementwise pass, XLA fuses it.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, None, :]                 # (bh, 1, s)
    if streaming:
        dq = pl.pallas_call(
            functools.partial(_dq_kernel_str, sm_scale=sm_scale,
                              causal=causal, block_q=block_q,
                              block_k=block_k),
            grid=(bh, s // block_q, s // block_k),
            in_specs=[
                _q_spec(block_q, d),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
                _q_spec(block_q, d),
                _lse_spec(block_q), _lse_spec(block_q),
            ],
            out_specs=_q_spec(block_q, d),
            out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            interpret=interpret,
        )(q, k, v, do, lse, delta)
        kb_spec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0))
        qs_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0))
        ls_spec = pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, j))
        dk, dv = pl.pallas_call(
            functools.partial(_dkv_kernel_str, sm_scale=sm_scale,
                              causal=causal, block_q=block_q,
                              block_k=block_k),
            grid=(bh, s // block_k, s // block_q),
            in_specs=[qs_spec, kb_spec, kb_spec, qs_spec, ls_spec, ls_spec],
            out_specs=[kb_spec, kb_spec],
            out_shape=[jax.ShapeDtypeStruct((bh, s, d), k.dtype),
                       jax.ShapeDtypeStruct((bh, s, d), v.dtype)],
            scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                            pltpu.VMEM((block_k, d), jnp.float32)],
            interpret=interpret,
        )(q, k, v, do, lse, delta)
        return dq, dk, dv

    full_spec2 = pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0))
    full_lse2 = pl.BlockSpec((1, 1, s), lambda b, i: (b, 0, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel_res, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_len=s),
        grid=(bh, s // block_q),
        in_specs=[_q_spec(block_q, d), full_spec2, full_spec2,
                  _q_spec(block_q, d), _lse_spec(block_q),
                  _lse_spec(block_q)],
        out_specs=_q_spec(block_q, d),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    kb2 = pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel_res, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_len=s),
        grid=(bh, s // block_k),
        in_specs=[full_spec2, kb2, kb2, full_spec2, full_lse2, full_lse2],
        out_specs=[kb2, kb2],
        out_shape=[jax.ShapeDtypeStruct((bh, s, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, s, d), v.dtype)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, sm_scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None,
                    streaming: Optional[bool] = None) -> jax.Array:
    """Blockwise (flash) attention.  q, k, v: [BH, S, D] -> [BH, S, D].

    sm_scale defaults to 1/sqrt(D).  interpret=None auto-selects the
    Pallas interpreter off-TPU so tests run on the CPU mesh.
    streaming=None auto-selects: K/V-resident kernels while 2*S*D fits the
    VMEM budget (fastest — K/V fetched once per batch*head), 3D-grid
    streaming kernels beyond (O(block*D) VMEM at any S).
    """
    out, _ = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k,
                        interpret, streaming)
    return out


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret,
               streaming):
    bh, s, d = q.shape
    if s % block_q or s % block_k:
        raise ValueError(
            f"seq_len {s} must divide block_q={block_q}, block_k={block_k}"
            " — use models.transformer.flash_attention_fn for the"
            " auto-fallback to dense attention")
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    out, lse = _fwd(q, k, v, scale, causal, block_q, block_k,
                    _use_interpret(interpret), _use_streaming(q, streaming))
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, streaming,
               residuals, g):
    d = residuals[0].shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    return _bwd(scale, causal, block_q, block_k, _use_interpret(interpret),
                _use_streaming(residuals[0], streaming), residuals, g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
