"""Ring attention: exact attention over sequence-sharded inputs.

Long-context support is absent from the reference (SURVEY §2.6/§5 — it
scales batch, never sequence) but is first-class here.  This is blockwise
ring attention: Q stays put, K/V blocks rotate around the 'sp' ring via
`lax.ppermute` while each device accumulates its queries' attention with an
online (flash-style) softmax.  Per-step traffic is one K/V block over ICI
neighbor links; memory is O(S_local), enabling sequences far beyond one
chip's HBM.

All shapes are static and the loop is a `lax.scan`, so XLA overlaps the
ppermute of block t+1 with the matmuls of block t (double buffering falls
out of the dataflow).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..common.compat import axis_size as _axis_size
from ..common.compat import shard_map as _shard_map

NEG_INF = float(jnp.finfo(jnp.float32).min)


def _block_attn(q, k, v, mask):
    """One blockwise attention contribution with running-max bookkeeping.

    q: [B,H,Sq,D], k/v: [B,H,Sk,D], mask: [Sq,Sk] bool (True = attend).
    Returns (out_unnorm [B,H,Sq,D] f32, lse terms): partial numerator and
    softmax statistics (m = row max, l = row sum) for online combination.
    """
    d = q.shape[-1]
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
    logits = logits / jnp.sqrt(jnp.asarray(d, jnp.float32))
    logits = jnp.where(mask, logits, NEG_INF)
    m = logits.max(axis=-1, keepdims=True)                     # [B,H,Sq,1]
    # All-masked rows: keep m finite so exp() is well-behaved.
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(logits - m_safe)
    p = jnp.where(mask, p, 0.0)
    l = p.sum(axis=-1, keepdims=True)                          # [B,H,Sq,1]
    o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)
    return o.astype(jnp.float32), m_safe, l


def ring_attention_shard(q, k, v, causal: bool, axis_name: str = "sp"):
    """Per-shard ring attention body (call under shard_map).

    q,k,v: [B, H, S_local, D] — this device's sequence block along a ring of
    `axis_size(axis_name)` devices.  Returns [B, H, S_local, D].
    """
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, H, S, D = q.shape

    # Send K/V to the next rank each step; after t steps this device holds
    # the block originally owned by (my - t) mod n.
    perm = [(i, (i + 1) % n) for i in range(n)]

    q_pos = my * S + jnp.arange(S)

    def step(carry, t):
        k_t, v_t, o, m, l = carry
        origin = (my - t) % n
        if causal:
            kv_pos = origin * S + jnp.arange(S)
            mask = q_pos[:, None] >= kv_pos[None, :]
        else:
            mask = jnp.ones((S, S), bool)
        o_t, m_t, l_t = _block_attn(q, k_t, v_t, mask)
        # Online-softmax merge of (o,m,l) with the new block's stats.
        m_new = jnp.maximum(m, m_t)
        c_old = jnp.exp(m - m_new)
        c_new = jnp.exp(m_t - m_new)
        o = o * c_old + o_t * c_new
        l = l * c_old + l_t * c_new
        k_n = lax.ppermute(k_t, axis_name, perm)
        v_n = lax.ppermute(v_t, axis_name, perm)
        return (k_n, v_n, o, m_new, l), None

    o0 = jnp.zeros((B, H, S, D), jnp.float32)
    m0 = jnp.full((B, H, S, 1), NEG_INF / 2, jnp.float32)
    l0 = jnp.zeros((B, H, S, 1), jnp.float32)
    (k, v, o, m, l), _ = lax.scan(step, (k, v, o0, m0, l0), jnp.arange(n))
    out = o / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def make_ring_attn_fn(mesh: Mesh, axis_name: str = "sp"):
    """Adaptor producing an `attn_fn(q, k, v, causal)` for
    models.transformer.forward: full-shape q/k/v come in (traced under the
    outer jit), the ring runs in a nested shard_map over the sequence axis.
    Heads stay sharded over 'tp' if the outer program shards them — the
    in_specs only constrain the sequence dim.
    """
    spec = P(None, None, axis_name, None)

    def attn_fn(q, k, v, causal):
        f = functools.partial(ring_attention_shard, causal=causal,
                              axis_name=axis_name)
        return _shard_map(f, mesh=mesh, in_specs=(spec, spec, spec),
                          out_specs=spec, check_vma=False)(q, k, v)
    return attn_fn


# ---------------------------------------------------------------------------
# Ulysses-style sequence parallelism: all-to-all re-shard seq <-> heads.
# ---------------------------------------------------------------------------
def ulysses_attention_shard(q, k, v, causal: bool, axis_name: str = "sp",
                            attn=None):
    """Per-shard Ulysses attention (call under shard_map).

    Inputs are sequence-sharded [B, H, S/n, D].  One all-to-all converts to
    head-sharded [B, H/n, S, D] (full sequence, subset of heads), dense
    attention runs locally, and a second all-to-all restores sequence
    sharding.  Communication is 2 all-to-alls instead of n ppermutes —
    better for moderate n on all-to-all-capable fabrics; requires
    num_heads % n == 0.
    """
    n = _axis_size(axis_name)

    def seq_to_heads(x):
        # [B, H, S/n, D] -> [B, H/n, S, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    if q.shape[1] % n != 0:
        raise ValueError(
            f"ulysses needs num_heads ({q.shape[1]}) divisible by the sp "
            f"axis size ({n}); use ring attention otherwise")
    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if attn is None:
        from ..models.transformer import dense_attention
        attn = dense_attention
    out = attn(qh, kh, vh, causal)
    return heads_to_seq(out)


def make_ulysses_attn_fn(mesh: Mesh, axis_name: str = "sp", attn="dense"):
    """Ulysses counterpart of make_ring_attn_fn.

    `attn` picks the per-shard attention over the full (gathered) sequence:
    "dense", "flash" (the Pallas kernel — Ulysses hands each shard the
    WHOLE sequence for a head subset, so the S x S logits the kernel
    avoids grow with total context, making this the natural pairing for
    long-context sp), or any callable (q, k, v, causal)."""
    spec = P(None, None, axis_name, None)
    if callable(attn):
        inner = attn
    else:
        from ..models import transformer as _tfm
        if attn not in _tfm._ATTN_IMPLS:
            raise ValueError(
                f"attn must be a callable or one of "
                f"{sorted(_tfm._ATTN_IMPLS)}; got {attn!r}")
        inner = _tfm._ATTN_IMPLS[attn]
        if attn == "flash":
            # An explicit flash request at gathered-sequence length must
            # not silently degrade to dense (that materializes the S x S
            # logits this pairing exists to avoid).
            inner = functools.partial(_tfm.flash_attention_fn, strict=True)

    def attn_fn(q, k, v, causal):
        f = functools.partial(ulysses_attention_shard, causal=causal,
                              axis_name=axis_name, attn=inner)
        return _shard_map(f, mesh=mesh, in_specs=(spec, spec, spec),
                          out_specs=spec, check_vma=False)(q, k, v)
    return attn_fn
