"""XLA collective data plane.

This module is the TPU replacement for the reference's entire C++ pipeline
(reference: byteps/common/core_loops.cc — NCCL reduce-scatter, D2H copy,
ps-lite ZPush/ZPull, H2D copy, NCCL all-gather).  On TPU the whole path is a
set of XLA collectives over mesh axes; what survives of the reference design
is its *scheduling structure*:

  - tensors are partitioned into <= BYTEPS_PARTITION_BYTES buckets
    (reference: operations.cc:140-180),
  - buckets are communicated in priority order — gradients produced first by
    the backward pass (the last layers) reduce first (reference:
    scheduled_queue.cc:82-102 orders by priority desc; plugins set
    priority = -declared_key, e.g. tensorflow/ops.cc:155-158),
  - the reduction is hierarchical when dp spans slices: reduce-scatter inside
    the ICI island, cross-island psum on the shard, all-gather back —
    the analog of NCCL-local-reduce → ps-push/pull → NCCL-broadcast
    (reference: core_loops.cc:188-267,536-616).

All functions here are traced under jit/shard_map; they are pure and
shape-static so XLA can pipeline the collectives with compute.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..common.compat import axis_size as _axis_size

from ..common.config import get_config

PyTree = Any

# Trace-time "local mode": when set, every collective in this module is the
# identity and axis sizes are 1.  This is the analog of the reference's
# single-worker non-distributed queue list, which skips PUSH/PULL entirely
# (reference: operations.cc:429-485) — build_train_step enables it when the
# mesh has one device so the whole step lowers to a plain jit with zero
# communication or sharding machinery.
_local_mode: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "byteps_tpu_local_mode", default=False)


@contextlib.contextmanager
def local_mode():
    tok = _local_mode.set(True)
    try:
        yield
    finally:
        _local_mode.reset(tok)


def is_local() -> bool:
    return _local_mode.get()


def axis_size(axis_name: str) -> int:
    return 1 if is_local() else _axis_size(axis_name)


# ---------------------------------------------------------------------------
# Thin wrappers (named to match the conceptual ops in SURVEY §2.6).
# ---------------------------------------------------------------------------
def all_reduce(x: jax.Array, axis_name: str = "dp") -> jax.Array:
    return x if is_local() else lax.psum(x, axis_name)


def all_gather(x: jax.Array, axis_name: str = "dp",
               axis: int = 0, tiled: bool = True) -> jax.Array:
    if is_local():
        return x if tiled else jnp.expand_dims(x, axis)
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x: jax.Array, axis_name: str = "dp",
                   axis: int = 0) -> jax.Array:
    if is_local():
        return x
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def ring_permute(x: jax.Array, axis_name: str, shift: int = 1) -> jax.Array:
    """Neighbor exchange on the ring — building block for ring attention."""
    n = _axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


# ---------------------------------------------------------------------------
# Bucketing: the partitioner applied to a flattened gradient pytree.
# ---------------------------------------------------------------------------
class BucketPlan:
    """Static plan mapping pytree leaves <-> priority-ordered buckets.

    Built once per (treedef, shapes) at trace time; the plan is pure Python
    metadata, so it adds nothing to the compiled graph.
    """

    def __init__(self, sizes: Sequence[int], partition_bytes: int,
                 itemsize: int, reverse: bool = True):
        # Leaf order is declaration order. The backward pass produces
        # gradients roughly in reverse declaration order, so communicating
        # buckets from the tail end first overlaps best — this is the
        # reference's priority = -declared_key in bucket form.  The
        # segment packing itself lives in the shared fusion planner
        # (common/fusion.py plan_segments), so the in-graph and PS-wire
        # planes agree on one bucket-composition algorithm.
        from ..common.fusion import plan_segments
        part_elems = max(1, partition_bytes // max(1, itemsize))
        # Each bucket is a list of (leaf_idx, start, length) segments.
        self.buckets: List[List[Tuple[int, int, int]]] = plan_segments(
            sizes, part_elems, reverse)
        self.sizes = list(sizes)

    def num_buckets(self) -> int:
        return len(self.buckets)


@functools.lru_cache(maxsize=256)
def _plan_cache(sizes: Tuple[int, ...], partition_bytes: int, itemsize: int,
                reverse: bool) -> BucketPlan:
    return BucketPlan(sizes, partition_bytes, itemsize, reverse)


def bucketed_tree_all_reduce(
    tree: PyTree,
    axis_name: str = "dp",
    average: bool = True,
    partition_bytes: Optional[int] = None,
    bucket_transform: Optional[Callable[[jax.Array, int], jax.Array]] = None,
) -> PyTree:
    """Partitioned, priority-ordered all-reduce of a gradient pytree.

    Each <=partition_bytes bucket is reduced by its own `lax.psum`, issued in
    backward-completion order so XLA can overlap early buckets' communication
    with the rest of the backward pass.  `bucket_transform`, when given, maps
    (bucket, bucket_index) -> reduced bucket and replaces the psum — this is
    the hook the compression subsystem uses.
    """
    if is_local() and bucket_transform is None:
        # Single-device: the sum over one worker is the identity and the
        # average divides by 1 — skip the bucket round-trip entirely, as the
        # reference's non-distributed queue list skips PUSH/PULL
        # (reference: operations.cc:429-485).
        return tree
    cfg = get_config()
    pb = partition_bytes or cfg.partition_bytes
    all_leaves, treedef = jax.tree.flatten(tree)
    # Zero-size leaves have nothing to communicate; pass them through.
    nonempty_idx = [i for i, l in enumerate(all_leaves) if l.size > 0]
    leaves = [all_leaves[i] for i in nonempty_idx]
    if not leaves:
        return tree
    # Promote everything to a common compute dtype for concat; remember
    # originals to cast back.
    orig_dtypes = [l.dtype for l in leaves]
    comm_dtype = jnp.result_type(*orig_dtypes)
    flat = [l.astype(comm_dtype).reshape(-1) for l in leaves]
    sizes = tuple(l.size for l in leaves)
    plan = _plan_cache(sizes, pb, jnp.dtype(comm_dtype).itemsize, True)

    denom = jnp.asarray(axis_size(axis_name), comm_dtype) if average else None

    out_segments: List[List[Optional[jax.Array]]] = [[] for _ in leaves]
    seg_starts: List[List[int]] = [[] for _ in leaves]
    for bi, bucket in enumerate(plan.buckets):
        # Named scope per bucket: the in-graph analog of the reference's
        # per-partition trace spans (global.cc:463-579) — the XLA profiler
        # attributes each bucket's collective to `byteps.bucket<N>` so the
        # per-bucket timeline is visible in a jax.profiler trace
        # (composition documented in docs/timeline.md).
        with jax.named_scope(f"byteps.bucket{bi}"):
            parts = [lax.dynamic_slice(flat[li], (start,), (length,))
                     for (li, start, length) in bucket]
            buf = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
            if bucket_transform is not None:
                buf = bucket_transform(buf, bi)
            else:
                buf = all_reduce(buf, axis_name)
            if average:
                buf = buf / denom
        off = 0
        for (li, start, length) in bucket:
            out_segments[li].append(lax.dynamic_slice(buf, (off,), (length,)))
            seg_starts[li].append(start)
            off += length
    reduced = []
    for li, leaf in enumerate(leaves):
        segs = out_segments[li]
        # Segments of one leaf arrive tail-first; restore offset order.
        order = sorted(range(len(segs)), key=lambda i: seg_starts[li][i])
        vec = jnp.concatenate([segs[i] for i in order]) if len(segs) > 1 \
            else segs[0]
        reduced.append(vec.reshape(leaf.shape).astype(orig_dtypes[li]))
    out_leaves = list(all_leaves)
    for i, r in zip(nonempty_idx, reduced):
        out_leaves[i] = r
    return jax.tree.unflatten(treedef, out_leaves)


def tree_all_reduce(tree: PyTree, axis_name: str = "dp",
                    average: bool = True) -> PyTree:
    """Unbucketed baseline: one psum per leaf (what naive DP in JAX does).

    Kept for benchmarking against the bucketed path.
    """
    def f(x):
        y = all_reduce(x, axis_name)
        if average:
            y = y / jnp.asarray(axis_size(axis_name), x.dtype)
        return y
    return jax.tree.map(f, tree)


# ---------------------------------------------------------------------------
# Hierarchical reduction over ('dcn_dp', 'ici_dp') — the two-level analog of
# the reference's NCCL-reduce-scatter → ps-push/pull → NCCL-all-gather.
# ---------------------------------------------------------------------------
def hierarchical_all_reduce(x: jax.Array, ici_axis: str = "ici_dp",
                            dcn_axis: str = "dcn_dp",
                            average: bool = False) -> jax.Array:
    """reduce-scatter on ICI, psum the shard over DCN, all-gather on ICI.

    Requires x's leading dim divisible by the ici axis size (callers pad flat
    buckets).  Cross-DCN traffic is 1/ici_size of the naive psum — the same
    bandwidth win the reference gets from summing locally before pushing
    (reference: docs/architecture.md:26-33).
    """
    shard = reduce_scatter(x, ici_axis, axis=0)
    shard = all_reduce(shard, dcn_axis)
    out = all_gather(shard, ici_axis, axis=0, tiled=True)
    if average:
        out = out / jnp.asarray(
            axis_size(ici_axis) * axis_size(dcn_axis), x.dtype)
    return out


def hierarchical_tree_all_reduce(tree: PyTree, ici_axis: str = "ici_dp",
                                 dcn_axis: str = "dcn_dp",
                                 average: bool = True,
                                 partition_bytes: Optional[int] = None
                                 ) -> PyTree:
    """Bucketed hierarchical all-reduce of a gradient pytree."""
    def transform(buf: jax.Array, bi: int) -> jax.Array:
        ici = axis_size(ici_axis)
        pad = (-buf.size) % ici
        if pad:
            buf = jnp.concatenate([buf, jnp.zeros((pad,), buf.dtype)])
        out = hierarchical_all_reduce(buf, ici_axis, dcn_axis, average=False)
        return out[:out.size - pad] if pad else out

    # average=False in the bucket, divide once at the end via the transform
    # caller; reuse bucketed path with explicit denominator.
    out = bucketed_tree_all_reduce(tree, axis_name=ici_axis, average=False,
                                   partition_bytes=partition_bytes,
                                   bucket_transform=transform)
    if average:
        n = axis_size(ici_axis) * axis_size(dcn_axis)
        out = jax.tree.map(lambda l: l / jnp.asarray(n, l.dtype), out)
    return out
