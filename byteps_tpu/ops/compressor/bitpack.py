"""Sign-bit packing as a Pallas TPU kernel (with a bit-identical jnp path).

XLA lowers naive minor-axis bit packing (reshape(-1, 8) + weighted sum)
poorly on TPU: the 8-wide minor dim forces cross-lane relayouts — 6.2 ms
per 64 MB round-trip on v5e (~22 GB/s effective), 4x off the elementwise
floor measured on the same chip (1.3 ms).  The fix is a layout the VPU
likes: view the flat input as (S, 32, 128) — a free, row-major-preserving
reshape — and pack the 32 sign bits of each lane column across the
SUBLANE axis into one uint32 lane (a sublane reduction, no lane crossing
at all).  Measured 64 MB round-trips: 1.53 ms as a Pallas kernel (the
default on TPU), 1.67 ms for the same format lowered by XLA (the jnp
fallback) — i.e. the layout is most of the win and the kernel keeps the
op at the memory-bound floor.

Wire format (internal to the collective plane; the PS tier's byte codec
lives in server/wire.py and is unchanged): uint32 words[ceil(n/4096)*128]
where element i of the zero-padded input contributes bit `(i//128) % 32`
of word `(i//4096)*128 + i%128`.  The jnp fallback implements the same
format so CPU tests and TPU runs interoperate bit-for-bit.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
SUBLANES = 32
GRAN = LANES * SUBLANES          # 4096 elements per (32, 128) tile
_MAX_BS = 32                     # max tiles per grid step (512KB f32)


def _block_tiles(s: int) -> int:
    """Tiles per grid block: the whole array when it fits one block
    (block == array satisfies the TPU tiling rule at any size), else a
    power-of-two divisor >= 8 (guaranteed because _num_tiles rounds tile
    counts above _MAX_BS up to a multiple of 8 — the uint32 words output
    needs its second-minor block dim 8-divisible)."""
    import math
    return s if s <= _MAX_BS else math.gcd(s, _MAX_BS)


def _resolve_impl(impl: Optional[str]) -> str:
    """None -> pallas on TPU, jnp elsewhere.  Explicit: "pallas" (compiled),
    "interpret" (pallas interpreter, for tests), "jnp"."""
    if impl is not None:
        return impl
    return "pallas" if jax.default_backend() == "tpu" else "jnp"


def _num_tiles(n: int) -> int:
    t = -(-n // GRAN)
    if t > _MAX_BS and t % 8:
        t += 8 - t % 8  # see _block_tiles; <= 7/33 overhead, only past 32
    return t


def _padded_len(n: int) -> int:
    return _num_tiles(n) * GRAN


def words_len(n: int) -> int:
    """Length of the packed uint32 array for an n-element input.

    One (32, 128) tile packs 4096 elements into 128 words, so inputs
    below 4096 elements pay a 512-byte wire floor, and tile counts above
    32 round up to a multiple of 8 (<= 21% overhead, worst at 33 tiles).
    Gradient buckets on the collective plane are partition-sized (<= 4MB,
    typically >= tens of tiles) where both effects are noise; tiny
    buckets are cheaper uncompressed — callers gate on size (the PS tier
    does via BYTEPS_MIN_COMPRESS_BYTES)."""
    return _padded_len(n) // SUBLANES


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------
def _pack_kernel(x_ref, w_ref):
    x = x_ref[:]                                  # (BS, 32, 128) f32
    bits = (x < 0).astype(jnp.uint32)
    row = jax.lax.broadcasted_iota(jnp.uint32, x.shape, 1)
    # Accumulate as int32 (unsigned reductions are unsupported in Mosaic);
    # bit positions are disjoint so the two's-complement sum is exact, and
    # the bitcast restores the uint32 view.
    acc = jnp.sum(jax.lax.bitcast_convert_type(bits << row, jnp.int32),
                  axis=1)
    w_ref[:] = jax.lax.bitcast_convert_type(acc, jnp.uint32)


def _unpack_kernel(w_ref, s_ref):
    w = w_ref[:]                                  # (BS, 128) u32
    shape = (w.shape[0], SUBLANES, LANES)
    row = jax.lax.broadcasted_iota(jnp.uint32, shape, 1)
    bits = (w[:, None, :] >> row) & jnp.uint32(1)
    # uint32 -> f32 casts are unsupported in Mosaic; the 0/1 payload is
    # identical through an int32 view.
    bits_i = jax.lax.bitcast_convert_type(bits, jnp.int32)
    # sign: bit 0 -> +1, bit 1 -> -1
    s_ref[:] = 1.0 - 2.0 * bits_i.astype(jnp.float32)


def _pack_pallas(x3, interpret):
    s = x3.shape[0]
    bs = _block_tiles(s)
    return pl.pallas_call(
        _pack_kernel,
        grid=(s // bs,),
        in_specs=[pl.BlockSpec((bs, SUBLANES, LANES), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((bs, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((s, LANES), jnp.uint32),
        interpret=interpret,
    )(x3)


def _unpack_pallas(w2, interpret):
    s = w2.shape[0]
    bs = _block_tiles(s)
    return pl.pallas_call(
        _unpack_kernel,
        grid=(s // bs,),
        in_specs=[pl.BlockSpec((bs, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((bs, SUBLANES, LANES), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((s, SUBLANES, LANES), jnp.float32),
        interpret=interpret,
    )(w2)


# ---------------------------------------------------------------------------
# jnp fallback, bit-identical wire format
# ---------------------------------------------------------------------------
def _pack_jnp(x3):
    bits = (x3 < 0).astype(jnp.uint32)
    row = jnp.arange(SUBLANES, dtype=jnp.uint32)[None, :, None]
    acc = jnp.sum(jax.lax.bitcast_convert_type(bits << row, jnp.int32),
                  axis=1)
    return jax.lax.bitcast_convert_type(acc, jnp.uint32)


def _unpack_jnp(w2):
    row = jnp.arange(SUBLANES, dtype=jnp.uint32)[None, :, None]
    bits = (w2[:, None, :] >> row) & jnp.uint32(1)
    return 1.0 - 2.0 * bits.astype(jnp.float32)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------
def pack_signs(x: jax.Array, impl: Optional[str] = None) -> jax.Array:
    """f32[n] -> uint32[words_len(n)] of sign bits (1 = negative)."""
    impl = _resolve_impl(impl)
    n = x.size
    if n == 0:
        return jnp.zeros((0,), jnp.uint32)
    pad = _padded_len(n) - n
    xf = x.astype(jnp.float32).ravel()
    if pad:
        # Padding with zeros: sign bit 0, reconstructed as +1 then sliced
        # away by unpack_signs.
        xf = jnp.concatenate([xf, jnp.zeros((pad,), jnp.float32)])
    x3 = xf.reshape(-1, SUBLANES, LANES)
    if impl == "jnp":
        return _pack_jnp(x3).ravel()
    return _pack_pallas(x3, impl == "interpret").ravel()


def unpack_signs(words: jax.Array, n: int,
                 impl: Optional[str] = None) -> jax.Array:
    """uint32[words_len(n)] -> f32[n] of +-1 signs."""
    impl = _resolve_impl(impl)
    if n == 0:
        return jnp.zeros((0,), jnp.float32)
    w2 = words.reshape(-1, LANES)
    if impl == "jnp":
        out = _unpack_jnp(w2)
    else:
        out = _unpack_pallas(w2, impl == "interpret")
    return out.ravel()[:n]


# ---------------------------------------------------------------------------
# b-bit level packing (dithering levels).  Same sublane-reduction layout as
# the sign kernels — view the input as (S, k, 128) with k = 32//b levels
# per uint32 word, pack across the SUBLANE axis (no lane crossing) — but
# lowered by XLA: the sign benchmark showed the layout is most of the win
# (jnp 1.67 ms vs kernel 1.53 ms per 64 MB), and level streams are u8-sized
# to begin with.  Fixed-width b bits stays fully vectorized where the
# reference's Elias-delta bitstream (compressor/utils.h:120-250) cannot.
# ---------------------------------------------------------------------------
def level_bits(s: int) -> int:
    """Wire bits per level for values 0..s."""
    return max(1, int(s).bit_length())


def _levels_per_word(b: int) -> int:
    return SUBLANES // b


def level_words_len(n: int, s: int) -> int:
    k = _levels_per_word(level_bits(s))
    return -(-n // (k * LANES)) * LANES


def pack_levels(level: jax.Array, s: int) -> jax.Array:
    """uint8[n] levels (each <= s) -> uint32[level_words_len(n, s)]."""
    b = level_bits(s)
    k = _levels_per_word(b)
    n = level.size
    if n == 0:
        return jnp.zeros((0,), jnp.uint32)
    pad = level_words_len(n, s) * k - n
    lv = level.astype(jnp.uint32).ravel()
    if pad:
        lv = jnp.concatenate([lv, jnp.zeros((pad,), jnp.uint32)])
    lv3 = lv.reshape(-1, k, LANES)
    row = (jnp.arange(k, dtype=jnp.uint32) * b)[None, :, None]
    # Disjoint bit fields: the int32 two's-complement sum equals the OR.
    acc = jnp.sum(jax.lax.bitcast_convert_type(lv3 << row, jnp.int32),
                  axis=1)
    return jax.lax.bitcast_convert_type(acc, jnp.uint32).ravel()


def unpack_levels(words: jax.Array, n: int, s: int) -> jax.Array:
    """uint32[level_words_len(n, s)] -> int32[n] levels."""
    b = level_bits(s)
    k = _levels_per_word(b)
    if n == 0:
        return jnp.zeros((0,), jnp.int32)
    w2 = words.reshape(-1, LANES)
    row = (jnp.arange(k, dtype=jnp.uint32) * b)[None, :, None]
    lv = (w2[:, None, :] >> row) & jnp.uint32((1 << b) - 1)
    return jax.lax.bitcast_convert_type(lv, jnp.int32).ravel()[:n]
