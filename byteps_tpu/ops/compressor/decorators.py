"""Error-feedback and momentum decorators.

Capability parity with the reference decorator chain
(reference: byteps/common/compressor/error_feedback.cc:22-34 — grad += e;
c = Compress(grad); e = grad - Decompress(c); momentum.cc:20-31 — Nesterov
m = mu*m + g; g += mu*m; layered momentum→ef→compressor by the registry,
compressor_registry.cc:39-56, with momentum worker-only).

Both are `InterCompressor` wrappers whose extra buffers live in the
functional `state`, replacing the reference's mutable `_error`/`_mom`
members.  The vanilla-EF learning-rate rescale (the reference reads an
mmap'd `lr.s` file written by the MXNet trainer,
impl/vanilla_error_feedback.cc) becomes an explicit `lr_scale` entry in the
state: when the training LR changes, call `set_lr_scale(opt_state,
new_lr / prev_lr)` on the optimizer state between steps — no file I/O in
the hot path.  With a constant LR the default 1.0 is already correct.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .base import InterCompressor, Payload, State


class ErrorFeedback(InterCompressor):
    """Vanilla error feedback around an inner compressor."""

    name = "ef"

    def __init__(self, inner: InterCompressor):
        self.inner = inner
        self.bidirectional = inner.bidirectional

    def init_state(self, n: int, dtype=jnp.float32) -> State:
        return {"inner": self.inner.init_state(n, dtype),
                "error": jnp.zeros((n,), jnp.float32),
                "lr_scale": jnp.ones((), jnp.float32)}

    def compress(self, buf: jax.Array, state: State) -> Tuple[Payload, State]:
        # reference: UpdateGradient = grad += scaled error
        corrected = buf.astype(jnp.float32) + state["lr_scale"] * state["error"]
        payload, inner_state = self.inner.compress(corrected, state["inner"])
        # reference: UpdateError = e = grad - Decompress(c)
        err = corrected - self.inner.decompress(payload, corrected.size)
        return payload, {"inner": inner_state, "error": err,
                         "lr_scale": state["lr_scale"]}

    def decompress(self, payload: Payload, n: int,
                   dtype=jnp.float32) -> jax.Array:
        return self.inner.decompress(payload, n, dtype)

    def payload_shapes(self, n: int, dtype=jnp.float32):
        return self.inner.payload_shapes(n, dtype)


def set_lr_scale(state: State, scale) -> State:
    """Refresh every ErrorFeedback `lr_scale` entry in `state` (any pytree —
    typically the whole optax opt_state) to `scale` = new_lr / prev_lr, the
    reference's vanilla-EF LR-ratio rescale
    (reference: impl/vanilla_error_feedback.cc, mxnet/__init__.py:326-331).
    """
    from jax.tree_util import DictKey, tree_map_with_path

    def f(path, leaf):
        if any(isinstance(k, DictKey) and k.key == "lr_scale"
               for k in path):
            return jnp.broadcast_to(
                jnp.asarray(scale, jnp.float32), leaf.shape)
        return leaf
    return tree_map_with_path(f, state)


class NesterovMomentum(InterCompressor):
    """Nesterov momentum applied before (EF +) compression; worker-only."""

    name = "momentum"

    def __init__(self, inner: InterCompressor, mu: float = 0.9):
        self.inner = inner
        self.mu = mu
        self.bidirectional = inner.bidirectional

    def init_state(self, n: int, dtype=jnp.float32) -> State:
        return {"inner": self.inner.init_state(n, dtype),
                "mom": jnp.zeros((n,), jnp.float32)}

    def compress(self, buf: jax.Array, state: State) -> Tuple[Payload, State]:
        g = buf.astype(jnp.float32)
        m = self.mu * state["mom"] + g          # m = mu*m + g
        g = g + self.mu * m                     # g += mu*m  (Nesterov)
        payload, inner_state = self.inner.compress(g, state["inner"])
        return payload, {"inner": inner_state, "mom": m}

    def decompress(self, payload: Payload, n: int,
                   dtype=jnp.float32) -> jax.Array:
        return self.inner.decompress(payload, n, dtype)

    def payload_shapes(self, n: int, dtype=jnp.float32):
        return self.inner.payload_shapes(n, dtype)
