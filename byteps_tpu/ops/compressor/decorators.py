"""Error-feedback and momentum decorators.

Capability parity with the reference decorator chain
(reference: byteps/common/compressor/error_feedback.cc:22-34 — grad += e;
c = Compress(grad); e = grad - Decompress(c); momentum.cc:20-31 — Nesterov
m = mu*m + g; g += mu*m; layered momentum→ef→compressor by the registry,
compressor_registry.cc:39-56, with momentum worker-only).

Both are `InterCompressor` wrappers whose extra buffers live in the
functional `state`, replacing the reference's mutable `_error`/`_mom`
members.  The vanilla-EF learning-rate rescale (the reference reads an
mmap'd `lr.s` file written by the MXNet trainer,
impl/vanilla_error_feedback.cc: `grad += (pre_lr/cur_lr) * error;
pre_lr = cur_lr`) becomes an explicit `lr_scale` entry in the state: when
the training LR changes, call `set_lr_scale(opt_state,
prev_lr / new_lr)` on the optimizer state between steps — no file I/O in
the hot path.  The scale is consumed by the NEXT compress and resets to
1.0, exactly the reference's one-shot `pre_lr = cur_lr`; with a constant
LR the default 1.0 is already correct.  (The ratio is prev/new: the
pending update `lr_prev * e` keeps its magnitude under the new LR when
the carried error becomes `(lr_prev/lr_new) * e`.)
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .base import InterCompressor, Payload, State


class ErrorFeedback(InterCompressor):
    """Vanilla error feedback around an inner compressor."""

    name = "ef"

    def __init__(self, inner: InterCompressor):
        self.inner = inner
        self.bidirectional = inner.bidirectional

    def init_state(self, n: int, dtype=jnp.float32) -> State:
        return {"inner": self.inner.init_state(n, dtype),
                "error": jnp.zeros((n,), jnp.float32),
                "lr_scale": jnp.ones((), jnp.float32)}

    def compress(self, buf: jax.Array, state: State) -> Tuple[Payload, State]:
        # reference: UpdateGradient = grad += (pre_lr/cur_lr) * error
        corrected = buf.astype(jnp.float32) + state["lr_scale"] * state["error"]
        payload, inner_state = self.inner.compress(corrected, state["inner"])
        # reference: UpdateError = e = grad - Decompress(c)
        err = corrected - self.inner.decompress(payload, corrected.size)
        # One-shot, like the reference's `pre_lr = cur_lr`: the scale must
        # not keep multiplying every subsequent round's fresh error.
        return payload, {"inner": inner_state, "error": err,
                         "lr_scale": jnp.ones_like(state["lr_scale"])}

    def decompress(self, payload: Payload, n: int,
                   dtype=jnp.float32) -> jax.Array:
        return self.inner.decompress(payload, n, dtype)

    def payload_shapes(self, n: int, dtype=jnp.float32):
        return self.inner.payload_shapes(n, dtype)


def set_lr_scale(state: State, scale) -> State:
    """Multiply every ErrorFeedback `lr_scale` entry in `state` (any pytree
    — typically the whole optax opt_state) by `scale` = prev_lr / new_lr,
    the reference's vanilla-EF LR-ratio rescale, consumed once by the next
    compress (reference: impl/vanilla_error_feedback.cc `pre_lr/cur_lr`,
    mxnet/__init__.py:326-331).  Multiplicative so consecutive calls with
    no compress in between (e.g. a schedule boundary coinciding with a
    skipped step) compose to r1*r2 — the same semantics as the wire and
    server planes, which multiply the stored error directly.
    """
    from jax.tree_util import DictKey, tree_map_with_path

    def f(path, leaf):
        if any(isinstance(k, DictKey) and k.key == "lr_scale"
               for k in path):
            return leaf * jnp.asarray(scale, jnp.float32)
        return leaf
    return tree_map_with_path(f, state)


class NesterovMomentum(InterCompressor):
    """Nesterov momentum applied before (EF +) compression; worker-only."""

    name = "momentum"

    def __init__(self, inner: InterCompressor, mu: float = 0.9):
        self.inner = inner
        self.mu = mu
        self.bidirectional = inner.bidirectional

    def init_state(self, n: int, dtype=jnp.float32) -> State:
        return {"inner": self.inner.init_state(n, dtype),
                "mom": jnp.zeros((n,), jnp.float32)}

    def compress(self, buf: jax.Array, state: State) -> Tuple[Payload, State]:
        g = buf.astype(jnp.float32)
        m = self.mu * state["mom"] + g          # m = mu*m + g
        g = g + self.mu * m                     # g += mu*m  (Nesterov)
        payload, inner_state = self.inner.compress(g, state["inner"])
        return payload, {"inner": inner_state, "mom": m}

    def decompress(self, payload: Payload, n: int,
                   dtype=jnp.float32) -> jax.Array:
        return self.inner.decompress(payload, n, dtype)

    def payload_shapes(self, n: int, dtype=jnp.float32):
        return self.inner.payload_shapes(n, dtype)
