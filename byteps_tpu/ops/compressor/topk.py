"""Top-k sparsification: keep the k largest-magnitude elements.

Capability parity with the reference topk compressor
(reference: byteps/common/compressor/impl/topk.cc:43-73 — abs-top-k into
(index, value) pairs via a heap).  TPU-native: `jax.lax.top_k` on |x| —
XLA lowers it to a sort-based kernel; the wire format is a fixed (k,) int32
index array + (k,) value array, 2k*4 bytes total.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .base import InterCompressor, Payload, State


class TopkCompressor(InterCompressor):
    name = "topk"

    def __init__(self, k: int):
        if k <= 0:
            raise ValueError(f"topk requires k > 0, got {k}")
        self.k = k

    def compress(self, buf: jax.Array, state: State) -> Tuple[Payload, State]:
        k = min(self.k, buf.size)
        x = buf.astype(jnp.float32)
        _, idx = jax.lax.top_k(jnp.abs(x), k)
        vals = x[idx]
        return {"idx": idx.astype(jnp.int32), "val": vals}, state

    def decompress(self, payload: Payload, n: int,
                   dtype=jnp.float32) -> jax.Array:
        out = jnp.zeros((n,), jnp.float32)
        # Indices are unique (top_k), so scatter-add == scatter.
        out = out.at[payload["idx"]].add(payload["val"])
        return out.astype(dtype)

    def payload_shapes(self, n: int, dtype=jnp.float32):
        k = min(self.k, n)
        return {"idx": ((k,), jnp.int32), "val": ((k,), jnp.float32)}
