"""Stochastic (dithered) quantization.

Capability parity with the reference dithering compressor
(reference: byteps/common/compressor/impl/dithering.cc:51-120): normalise by
max-norm or L2-norm, map magnitudes onto s quantization levels with a
*linear* or *natural* (power-of-two) partition, round stochastically so the
quantizer is unbiased, and ship sign+level.

Wire-format redesign for TPU (flagged in SURVEY §7): the reference packs
levels with Elias-delta variable-length bitstreams — hostile to vector
units.  This build packs levels FIXED-WIDTH at b = ceil(log2(s+1)) bits
into uint32 words (sublane layout, ops/compressor/bitpack.pack_levels) +
packed sign bits + the norm scalar: shape-static, fully vectorised, same
accuracy contract (the quantizer itself is identical and unbiased; only
the entropy-coding stage differs), and within ~1.3x of the Elias-delta
wire density for typical gradients (s=15: 4+1 bits/elem).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .base import (InterCompressor, Payload, State, rng_uniform, seed_state)
from .bitpack import (level_words_len, pack_levels, pack_signs,
                      unpack_levels, unpack_signs, words_len)


class DitheringCompressor(InterCompressor):
    name = "dithering"

    def __init__(self, s: int = 127, seed: int = 2020,
                 partition: str = "linear", normalize: str = "max"):
        if not (0 < s <= 127):
            raise ValueError(f"dithering levels must be in (0,127], got {s}")
        if partition not in ("linear", "natural"):
            raise ValueError(f"unknown partition {partition!r}")
        if normalize not in ("max", "l2"):
            raise ValueError(f"unknown normalize {normalize!r}")
        self.s = s
        self.seed = seed
        self.partition = partition
        self.normalize = normalize

    def init_state(self, n: int, dtype=jnp.float32) -> State:
        return {"rng": seed_state(self.seed, n)}

    def _levels(self) -> jax.Array:
        """Quantization points in [0,1], length s+1 (level 0 == 0)."""
        s = self.s
        if self.partition == "linear":
            return jnp.arange(s + 1, dtype=jnp.float32) / s
        # natural: 0, 2^-(s-1), ..., 2^-1, 2^0 — denser near zero.
        pts = 2.0 ** jnp.arange(-(s - 1), 1, dtype=jnp.float32)
        return jnp.concatenate([jnp.zeros((1,), jnp.float32), pts])

    def compress(self, buf: jax.Array, state: State) -> Tuple[Payload, State]:
        n = buf.size
        x = buf.astype(jnp.float32)
        if self.normalize == "max":
            norm = jnp.max(jnp.abs(x))
        else:
            norm = jnp.sqrt(jnp.sum(x * x))
        norm = jnp.maximum(norm, jnp.finfo(jnp.float32).tiny)
        mag = jnp.abs(x) / norm                      # in [0, 1]
        levels = self._levels()                      # [s+1] ascending
        # Find bracket [levels[j], levels[j+1]] containing mag, then round
        # stochastically: P(up) = (mag - lo) / (hi - lo)  -> unbiased.
        j = jnp.clip(jnp.searchsorted(levels, mag, side="right") - 1,
                     0, self.s - 1)
        lo = levels[j]
        hi = levels[j + 1]
        p_up = jnp.where(hi > lo, (mag - lo) / jnp.maximum(hi - lo, 1e-30),
                         0.0)
        u, rng = rng_uniform(state["rng"][:n])
        level = (j + (u < p_up)).astype(jnp.uint8)
        new_state = {"rng": state["rng"].at[:n].set(rng)}
        # Sign stream rides the sublane-packed bitpack wire (Pallas on
        # TPU); levels pack fixed-width at ceil(log2(s+1)) bits in the
        # same sublane layout (bitpack.pack_levels).
        return ({"level_words": pack_levels(level, self.s),
                 "signs": pack_signs(x),
                 "norm": norm[None]}, new_state)

    def decompress(self, payload: Payload, n: int,
                   dtype=jnp.float32) -> jax.Array:
        levels = self._levels()
        mag = levels[unpack_levels(payload["level_words"], n, self.s)]
        sign = unpack_signs(payload["signs"], n)      # +-1 f32
        return (sign * mag * payload["norm"][0]).astype(dtype)

    def payload_shapes(self, n: int, dtype=jnp.float32):
        return {"level_words": ((level_words_len(n, self.s),), jnp.uint32),
                "signs": ((words_len(n),), jnp.uint32),
                "norm": ((1,), jnp.float32)}
