"""Random-k sparsification: k elements at seeded-pseudorandom indices.

Capability parity with the reference randomk compressor
(reference: byteps/common/compressor/impl/randomk.cc:24-61 — k (idx,val)
pairs drawn from a seeded xorshift128+).  The TPU build draws k lanes of
xorshift32 (see base.py for why 32-bit) and maps each to an index by the
same `u * n` truncation the test-side numpy replica uses, so selection is
bit-replayable.  Indices may collide (as in the reference); decompress
scatter-adds, and compress reads whatever value lives at each drawn index.

State = the k-lane uint32 PRNG state, advanced once per compress call, so
successive steps draw fresh index sets deterministically from the seed.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .base import InterCompressor, Payload, State, seed_state, xorshift32


class RandomkCompressor(InterCompressor):
    name = "randomk"

    def __init__(self, k: int, seed: int = 2020):
        if k <= 0:
            raise ValueError(f"randomk requires k > 0, got {k}")
        self.k = k
        self.seed = seed

    def init_state(self, n: int, dtype=jnp.float32) -> State:
        return {"rng": seed_state(self.seed, self.k)}

    def compress(self, buf: jax.Array, state: State) -> Tuple[Payload, State]:
        n = buf.size
        k = min(self.k, n)
        rng = xorshift32(state["rng"])
        u = (rng >> jnp.uint32(8)).astype(jnp.float32) * (1.0 / (1 << 24))
        idx = jnp.minimum((u[:k] * n).astype(jnp.int32), n - 1)
        vals = buf.astype(jnp.float32)[idx]
        return {"idx": idx, "val": vals}, {"rng": rng}

    def decompress(self, payload: Payload, n: int,
                   dtype=jnp.float32) -> jax.Array:
        out = jnp.zeros((n,), jnp.float32)
        out = out.at[payload["idx"]].add(payload["val"])
        return out.astype(dtype)

    def payload_shapes(self, n: int, dtype=jnp.float32):
        k = min(self.k, n)
        return {"idx": ((k,), jnp.int32), "val": ((k,), jnp.float32)}
