"""Compressed distributed gradient reduction.

The reference's compressed push-pull: each worker compresses its local
gradient partition, the server decompresses every worker's payload, sums,
re-compresses (bidirectional compressors), and workers decompress the pull
(reference: core_loops.cc:496-534 COMPRESS/DECOMPRESS stages +
server/server.cc:86-207 engine decompress-sum-compress).

TPU-native data plane: there is no server hop inside a slice — the payload
is `all_gather`ed over the dp axis (wire volume = compressed bytes x world,
vs 2 x full gradient for ring all-reduce, a win whenever the ratio beats
world/2... i.e. aggressive compressors + small dp groups, or the DCN axis of
a hierarchical mesh where bandwidth is scarcest), each peer's contribution
is decompressed on-device (vmap), summed, and — for bidirectional
compressors — requantized with a server-side compressor state so the result
matches what a PS round-trip would produce.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ...common.config import get_config
from .. import collectives
from ..collectives import _plan_cache
from .base import InterCompressor

PyTree = Any


def server_side(compressor: InterCompressor) -> InterCompressor:
    """The compressor the 'server' leg runs: momentum stripped, matching the
    reference registry's server instantiation
    (reference: compressor_registry.cc:49-52)."""
    from .decorators import NesterovMomentum
    while isinstance(compressor, NesterovMomentum):
        compressor = compressor.inner
    return compressor


def _bucketize(tree: PyTree, partition_bytes: Optional[int]):
    """Flatten a pytree into the standard priority-ordered bucket list.
    Returns (buckets, rebuild) where rebuild maps reduced bucket vectors back
    to the original tree structure."""
    cfg = get_config()
    pb = partition_bytes or cfg.partition_bytes
    all_leaves, treedef = jax.tree.flatten(tree)
    nonempty = [i for i, l in enumerate(all_leaves) if l.size > 0]
    leaves = [all_leaves[i] for i in nonempty]
    if not leaves:
        return [], lambda bufs: tree, None
    orig_dtypes = [l.dtype for l in leaves]
    comm_dtype = jnp.result_type(*orig_dtypes)
    flat = [l.astype(comm_dtype).reshape(-1) for l in leaves]
    sizes = tuple(l.size for l in leaves)
    plan = _plan_cache(sizes, pb, jnp.dtype(comm_dtype).itemsize, True)

    buckets = []
    for bucket in plan.buckets:
        parts = [lax.dynamic_slice(flat[li], (start,), (length,))
                 for (li, start, length) in bucket]
        buckets.append(jnp.concatenate(parts) if len(parts) > 1 else parts[0])

    def rebuild(reduced_bufs: List[jax.Array]) -> PyTree:
        segs: List[List[jax.Array]] = [[] for _ in leaves]
        starts: List[List[int]] = [[] for _ in leaves]
        for buf, bucket in zip(reduced_bufs, plan.buckets):
            off = 0
            for (li, start, length) in bucket:
                segs[li].append(lax.dynamic_slice(buf, (off,), (length,)))
                starts[li].append(start)
                off += length
        out_leaves = list(all_leaves)
        for j, li in enumerate(nonempty):
            order = sorted(range(len(segs[j])), key=lambda i: starts[j][i])
            vec = jnp.concatenate([segs[j][i] for i in order]) \
                if len(segs[j]) > 1 else segs[j][0]
            out_leaves[li] = vec.reshape(leaves[j].shape).astype(orig_dtypes[j])
        return jax.tree.unflatten(treedef, out_leaves)

    return buckets, rebuild, plan


def init_compression_state(tree: PyTree, compressor: InterCompressor,
                           partition_bytes: Optional[int] = None) -> Any:
    """Per-bucket compressor state for a gradient pytree (worker side plus,
    for bidirectional compressors, a server-side requantization state)."""
    buckets, _, _ = _bucketize(tree, partition_bytes)
    worker = tuple(compressor.init_state(int(b.size)) for b in buckets)
    srv = server_side(compressor)
    server = tuple(srv.init_state(int(b.size)) for b in buckets) \
        if compressor.bidirectional else None
    return {"worker": worker, "server": server}


def compressed_tree_all_reduce(
    tree: PyTree,
    compressor: InterCompressor,
    state: Any = None,
    axis_name: str = "dp",
    average: bool = True,
    partition_bytes: Optional[int] = None,
    two_way: Optional[bool] = None,
) -> Tuple[PyTree, Any]:
    """All-reduce `tree` with compressed wire traffic.

    Returns (reduced_tree, new_state).  `state` must come from
    `init_compression_state` (or be None for stateless compressors).
    `two_way=None` defaults to the compressor's bidirectional flag.
    """
    buckets, rebuild, _ = _bucketize(tree, partition_bytes)
    if not buckets:
        return tree, state
    if two_way is None:
        two_way = compressor.bidirectional
    if state is None:
        state = init_compression_state(tree, compressor, partition_bytes)

    world = collectives.axis_size(axis_name)
    srv = server_side(compressor)
    new_worker, new_server, reduced = [], [], []
    for bi, buf in enumerate(buckets):
        n = int(buf.size)
        if compressor.payload_bytes(n) >= n * buf.dtype.itemsize:
            # Compression would EXPAND this bucket (wire-format floors:
            # e.g. the sign stream's 512B tile, bitpack.words_len) — ship
            # it raw, the analog of the PS tier's min-compress gate
            # (server/client.py BYTEPS_MIN_COMPRESS_BYTES).
            summed = collectives.all_reduce(buf, axis_name)
            if average:
                summed = summed / world
            reduced.append(summed)
            new_worker.append(state["worker"][bi])
            if two_way:
                # Keep server-state alignment with the compressed path,
                # which appends one entry per bucket whenever two_way.
                new_server.append(state["server"][bi]
                                  if state["server"] is not None
                                  else srv.init_state(n))
            continue
        payload, wst = compressor.compress(buf, state["worker"][bi])
        new_worker.append(wst)
        # push: everyone ships its payload to everyone (the TPU "server").
        gathered = jax.tree.map(
            lambda a: collectives.all_gather(a, axis_name, axis=0,
                                             tiled=False),
            payload)
        summed = jax.vmap(
            lambda p: compressor.decompress(p, n))(gathered).sum(axis=0)
        if two_way:
            # Server-side requantize before the pull leg (momentum stripped,
            # as the reference server does).
            sst = state["server"][bi] if state["server"] is not None \
                else srv.init_state(n)
            payload2, sst = srv.compress(summed, sst)
            summed = srv.decompress(payload2, n)
            new_server.append(sst)
        if average:
            summed = summed / world
        reduced.append(summed)

    new_state = {"worker": tuple(new_worker),
                 "server": tuple(new_server) if new_server else
                 state.get("server")}
    return rebuild(reduced), new_state


def compression_ratio(tree: PyTree, compressor: InterCompressor,
                      partition_bytes: Optional[int] = None) -> float:
    """Raw bytes / wire bytes for one push leg (telemetry helper)."""
    buckets, _, _ = _bucketize(tree, partition_bytes)
    raw = sum(int(b.size) * 4 for b in buckets)
    wire = sum(compressor.payload_bytes(int(b.size)) for b in buckets)
    return raw / max(wire, 1)
