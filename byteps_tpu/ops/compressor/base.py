"""Inter-node gradient compressor interface (level 2 of the two-level design).

The reference's compressor interface is byte-buffer in/out with internal
scratch (reference: byteps/common/compressor/compressor.h:53-127 —
Compress/Decompress/FastUpdateError).  A byte-stream API is hostile to XLA
(dynamic sizes, host round-trips), so the TPU-native contract is functional
and shape-static:

    payload, state' = compressor.compress(buf, state)     # traced, on-device
    buf'            = compressor.decompress(payload, n)   # traced, on-device

  - `buf` is a flat f32/bf16 vector (one <=4MB bucket, the analog of one
    reference partition/key).
  - `payload` is a dict of fixed-shape arrays — the wire format.  Its total
    byte size is what travels over ICI/DCN; `payload_bytes()` reports it so
    telemetry/benchmarks can measure the compression ratio.
  - `state` carries the PRNG counters and any decorator buffers (error
    feedback, momentum), threaded functionally — the TPU replacement for the
    reference's mutable `_buf`/`_error` members.

All compressors are registered by name with string kwargs, mirroring the
reference registry (compressor_registry.cc:39-56), so user-facing config is
identical: {"compressor": "onebit", "ef": "vanilla", ...}.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Payload = Dict[str, jax.Array]
State = Any


class InterCompressor:
    """Base class. Subclasses are stateless Python objects; all mutable state
    flows through `state` pytrees so everything jits cleanly."""

    name: str = "base"
    #: True if the merged (summed) gradient should be re-compressed before
    #: being "pulled" back — the reference's bidirectional compressors do
    #: this on the server (reference: impl/onebit.h "bidirectional").
    bidirectional: bool = False

    def init_state(self, n: int, dtype=jnp.float32) -> State:
        """Per-bucket state for a bucket of n elements."""
        del n, dtype
        return ()

    def compress(self, buf: jax.Array, state: State) -> Tuple[Payload, State]:
        raise NotImplementedError

    def decompress(self, payload: Payload, n: int,
                   dtype=jnp.float32) -> jax.Array:
        raise NotImplementedError

    def payload_bytes(self, n: int, dtype=jnp.float32) -> int:
        """Wire bytes for an n-element bucket (for telemetry/ratio checks
        and the expansion gate in reduce.py).  Pure host math: shapes are
        static, and this must stay traceable-context-safe (it runs inside
        shard_map traces)."""
        import math
        shapes = self.payload_shapes(n, dtype)
        return sum(math.prod(int(x) for x in s) * jnp.dtype(d).itemsize
                   for s, d in shapes.values())

    def payload_shapes(self, n: int, dtype=jnp.float32) -> Dict[str, tuple]:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# TPU-friendly deterministic PRNG: xorshift32, vectorised.
#
# The reference seeds an xorshift128+ (compressor/utils.h:74-117) so its
# Python tests can replay the exact index/rounding choices
# (tests/utils.py:31-52).  64-bit integer ops are emulated (slow) on TPU
# vector units, so this build standardises on xorshift32 — same replayability
# contract (tests/test_compressor.py re-implements it in numpy), full vector
# width on device.
# ---------------------------------------------------------------------------
def xorshift32(state: jax.Array) -> jax.Array:
    """One xorshift32 step. state: uint32 array (any shape), nonzero."""
    x = state
    x = x ^ (x << jnp.uint32(13))
    x = x ^ (x >> jnp.uint32(17))
    x = x ^ (x << jnp.uint32(5))
    return x


def rng_uniform(state: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Advance the per-lane PRNG; return (u in [0,1) f32, new_state)."""
    s = xorshift32(state)
    # 24 mantissa-safe bits.
    u = (s >> jnp.uint32(8)).astype(jnp.float32) * (1.0 / (1 << 24))
    return u, s


def seed_state(seed: int, n: int) -> jax.Array:
    """n independent nonzero uint32 lanes from a scalar seed (splitmix-style
    lane spreading, then one warmup round)."""
    lanes = jnp.arange(1, n + 1, dtype=jnp.uint32)
    s = lanes * jnp.uint32(2654435761) + jnp.uint32(seed | 1)
    s = jnp.where(s == 0, jnp.uint32(0x9E3779B9), s)
    return xorshift32(s)
