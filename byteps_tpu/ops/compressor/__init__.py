"""Inter-node gradient compression subsystem (level 2).

TPU-native re-design of the reference compressor stack
(reference: byteps/common/compressor/ — see SURVEY §2.2): onebit, topk,
randomk, dithering compressors; error-feedback and Nesterov-momentum
decorators; a string-kwargs registry; and the compressed collective
reduction that replaces the compressed push-pull path.
"""

from .base import (InterCompressor, Payload, State, xorshift32, rng_uniform,
                   seed_state)
from .onebit import OnebitCompressor
from .topk import TopkCompressor
from .randomk import RandomkCompressor
from .dithering import DitheringCompressor
from .decorators import ErrorFeedback, NesterovMomentum, set_lr_scale
from .registry import create, register, known_compressors
from .reduce import (compressed_tree_all_reduce, init_compression_state,
                     compression_ratio, server_side)

__all__ = [
    "InterCompressor", "Payload", "State",
    "xorshift32", "rng_uniform", "seed_state",
    "OnebitCompressor", "TopkCompressor", "RandomkCompressor",
    "DitheringCompressor", "ErrorFeedback", "NesterovMomentum",
    "set_lr_scale", "server_side",
    "create", "register", "known_compressors",
    "compressed_tree_all_reduce", "init_compression_state",
    "compression_ratio",
]
