"""Onebit (sign) compression — 32:1, optionally scaled.

Capability parity with the reference onebit compressor
(reference: byteps/common/compressor/impl/onebit.cc:34-66): keep only the
sign of each element, packed 8-per-byte, with an optional scale = mean(|x|)
so the reconstruction is `scale * sign(x)` instead of `±1`.  Bidirectional:
the merged gradient is re-compressed before the pull leg, as the reference
server does.

TPU-native wire format: a uint32 array of sign-bit words in the sublane-
packed layout of ops/compressor/bitpack.py (a Pallas kernel on TPU, 4x
the throughput of byte-wise packing; see that module's header for the
measured numbers) plus a single f32 scale.  This wire format is internal
to the collective plane; the PS tier's byte codec (server/wire.py,
bit-matched to the C++ server) is separate and unchanged.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from .base import InterCompressor, Payload, State
from .bitpack import pack_signs, unpack_signs, words_len


class OnebitCompressor(InterCompressor):
    name = "onebit"
    bidirectional = True

    def __init__(self, scaled: bool = True):
        self.scaled = scaled

    def compress(self, buf: jax.Array, state: State) -> Tuple[Payload, State]:
        n = buf.size
        # sign bit: 1 where x < 0 (zero counts as +, matching sign(0)=+1
        # reconstruction below).
        words = pack_signs(buf)
        if self.scaled:
            scale = jnp.abs(buf.astype(jnp.float32)).sum() / jnp.maximum(n, 1)
        else:
            scale = jnp.ones((), jnp.float32)
        return {"bits": words, "scale": scale[None]}, state

    def decompress(self, payload: Payload, n: int,
                   dtype=jnp.float32) -> jax.Array:
        sign = unpack_signs(payload["bits"], n)       # +-1 f32
        return (sign * payload["scale"][0]).astype(dtype)

    def payload_shapes(self, n: int, dtype=jnp.float32):
        return {"bits": ((words_len(n),), jnp.uint32),
                "scale": ((1,), jnp.float32)}
