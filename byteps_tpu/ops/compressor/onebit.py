"""Onebit (sign) compression — 32:1, optionally scaled.

Capability parity with the reference onebit compressor
(reference: byteps/common/compressor/impl/onebit.cc:34-66): keep only the
sign of each element, packed 8-per-byte, with an optional scale = mean(|x|)
so the reconstruction is `scale * sign(x)` instead of `±1`.  Bidirectional:
the merged gradient is re-compressed before the pull leg, as the reference
server does.

TPU-native wire format: a uint8 array of ceil(n/8) bytes (sign bits) plus a
single f32 scale.  Packing is a reshape + dot with powers of two — one small
matmul, no scalar loops, so it vectorises on the VPU/MXU.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from .base import InterCompressor, Payload, State


def _pack_bits(bits: jax.Array) -> jax.Array:
    """bits: [n] in {0,1} (n % 8 == 0) -> uint8 [n/8]; bit i is LSB-first."""
    b = bits.reshape(-1, 8).astype(jnp.uint8)
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))
    return (b * weights).sum(axis=1).astype(jnp.uint8)


def _unpack_bits(packed: jax.Array) -> jax.Array:
    """uint8 [m] -> [m*8] in {0,1}, LSB-first."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    return ((packed[:, None] >> shifts) & jnp.uint8(1)).reshape(-1)


class OnebitCompressor(InterCompressor):
    name = "onebit"
    bidirectional = True

    def __init__(self, scaled: bool = True):
        self.scaled = scaled

    def compress(self, buf: jax.Array, state: State) -> Tuple[Payload, State]:
        n = buf.size
        pad = (-n) % 8
        x = buf.astype(jnp.float32)
        if pad:
            x = jnp.concatenate([x, jnp.zeros((pad,), jnp.float32)])
        # sign bit: 1 where x < 0 (zero counts as +, matching sign(0)=+1
        # reconstruction below).
        bits = (x < 0).astype(jnp.uint8)
        packed = _pack_bits(bits)
        if self.scaled:
            scale = jnp.abs(buf.astype(jnp.float32)).sum() / jnp.maximum(n, 1)
        else:
            scale = jnp.ones((), jnp.float32)
        return {"bits": packed, "scale": scale[None]}, state

    def decompress(self, payload: Payload, n: int,
                   dtype=jnp.float32) -> jax.Array:
        bits = _unpack_bits(payload["bits"])[:n]
        sign = 1.0 - 2.0 * bits.astype(jnp.float32)   # 0 -> +1, 1 -> -1
        return (sign * payload["scale"][0]).astype(dtype)

    def payload_shapes(self, n: int, dtype=jnp.float32):
        return {"bits": (((n + 7) // 8,), jnp.uint8),
                "scale": ((1,), jnp.float32)}
