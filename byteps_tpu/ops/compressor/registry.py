"""String-kwargs compressor registry.

Capability parity with the reference registry
(reference: byteps/common/compressor/compressor_registry.cc:39-56 — layers
momentum → error-feedback → compressor from string kwargs; the server-side
instantiation skips momentum).  Accepts both short keys ("compressor") and
the reference's fully-prefixed keys ("byteps_compressor_type"), so user
configs written for the reference carry over verbatim
(reference: byteps/mxnet/__init__.py:236-317 builds these kwargs).
"""

from __future__ import annotations

from typing import Callable, Dict

from .base import InterCompressor
from .decorators import ErrorFeedback, NesterovMomentum
from .dithering import DitheringCompressor
from .onebit import OnebitCompressor
from .randomk import RandomkCompressor
from .topk import TopkCompressor

_FACTORIES: Dict[str, Callable[..., InterCompressor]] = {}


def register(name: str):
    def deco(fn):
        _FACTORIES[name] = fn
        return fn
    return deco


@register("onebit")
def _make_onebit(kw):
    return OnebitCompressor(scaled=_get_bool(kw, "onebit_scaling", True))


@register("topk")
def _make_topk(kw):
    return TopkCompressor(k=int(_get(kw, "k", 0)))


@register("randomk")
def _make_randomk(kw):
    return RandomkCompressor(k=int(_get(kw, "k", 0)),
                             seed=int(_get(kw, "seed", 2020)))


@register("dithering")
def _make_dithering(kw):
    return DitheringCompressor(
        s=int(_get(kw, "k", 127)),
        seed=int(_get(kw, "seed", 2020)),
        partition=str(_get(kw, "partition", "linear")),
        normalize=str(_get(kw, "normalize", "max")))


def _get(kw: dict, name: str, default):
    """Look up `name`, `compressor_<name>`, or `byteps_compressor_<name>`."""
    for key in (name, f"compressor_{name}", f"byteps_compressor_{name}"):
        if key in kw:
            return kw[key]
    return default


def _get_bool(kw: dict, name: str, default: bool) -> bool:
    v = _get(kw, name, default)
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes", "on")
    return bool(v)


def parse_ef(kw: dict) -> bool:
    """Shared EF-kwargs parse (JAX registry + PS wire must accept the
    exact same strings — a divergence would make a config valid on one
    plane and a ValueError on the other)."""
    ef = (kw.get("ef") or kw.get("ef_type")
          or kw.get("byteps_error_feedback_type"))
    if ef and ef not in ("vanilla", "true", "1"):
        raise ValueError(f"unknown error-feedback type {ef!r}")
    return bool(ef)


def parse_momentum(kw: dict) -> float:
    """Shared momentum-kwargs parse; returns mu (0.0 = momentum off)."""
    mom = (kw.get("momentum") or kw.get("momentum_type")
           or kw.get("byteps_momentum_type"))
    if not mom:
        return 0.0
    if mom not in ("nesterov", "true", "1"):
        raise ValueError(f"unknown momentum type {mom!r}")
    return float(kw.get("momentum_mu", kw.get("byteps_momentum_mu", 0.9)))


def create(kwargs: dict, server: bool = False) -> InterCompressor:
    """Build the layered compressor from string kwargs.

    Layering order (outermost first): momentum → error-feedback → compressor,
    with momentum skipped on the server, exactly as the reference registry
    does (compressor_registry.cc:39-56).
    """
    kw = dict(kwargs)
    ctype = (kw.get("compressor") or kw.get("compressor_type")
             or kw.get("byteps_compressor_type"))
    if ctype is None:
        raise ValueError(f"no compressor type in kwargs: {sorted(kw)}")
    if ctype not in _FACTORIES:
        raise ValueError(
            f"unknown compressor {ctype!r}; known: {sorted(_FACTORIES)}")
    comp = _FACTORIES[ctype](kw)

    if parse_ef(kw):
        comp = ErrorFeedback(comp)

    mu = parse_momentum(kw)
    if mu and not server:
        comp = NesterovMomentum(comp, mu=mu)
    return comp


def known_compressors():
    return sorted(_FACTORIES)
