"""Training callbacks — parity with the reference's Keras callback suite.

The reference ships BroadcastGlobalVariables, MetricAverage,
LearningRateSchedule and LearningRateWarmup callbacks for Keras
(reference: byteps/_keras/callbacks.py:23-196, byteps/keras/callbacks.py).
The JAX-native equivalents are framework-agnostic hooks driven by a plain
training loop plus optax schedule builders (warmup folds into the schedule
rather than mutating an optimizer's lr in place).

    cbs = [BroadcastGlobalVariablesCallback(0), MetricAverageCallback()]
    for cb in cbs: state = cb.on_train_begin(state)
    ...
    for cb in cbs: metrics = cb.on_epoch_end(metrics)
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import optax

PyTree = Any


class Callback:
    def on_train_begin(self, state: PyTree) -> PyTree:
        return state

    def on_epoch_end(self, metrics: Dict[str, Any]) -> Dict[str, Any]:
        return metrics


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast initial state from root_rank to every worker, the
    reference's pre-training consistency step
    (reference: _keras/callbacks.py:23-49)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def on_train_begin(self, state: PyTree) -> PyTree:
        from . import common  # noqa: F401  (package import path)
        from .common.api import broadcast_parameters
        return broadcast_parameters(state, self.root_rank)


class MetricAverageCallback(Callback):
    """Average epoch metrics across workers before reporting
    (reference: _keras/callbacks.py:52-91)."""

    def on_epoch_end(self, metrics: Dict[str, Any]) -> Dict[str, Any]:
        from .common.api import push_pull
        import jax.numpy as jnp
        return {k: float(push_pull(jnp.asarray(v, jnp.float32),
                                   name=f"metric.{k}", average=True))
                for k, v in metrics.items()}


def warmup_schedule(base_lr: float, warmup_steps: int,
                    after: Optional[optax.Schedule] = None,
                    warmup_init_factor: float = 1.0 / 3) -> optax.Schedule:
    """LearningRateWarmupCallback as an optax schedule: ramp from
    base_lr*init_factor to base_lr over warmup_steps, then hand off to
    `after` (reference: _keras/callbacks.py:144-196 — gradual warmup from
    the 'Accurate, Large Minibatch SGD' recipe)."""
    ramp = optax.linear_schedule(base_lr * warmup_init_factor, base_lr,
                                 warmup_steps)
    if after is None:
        return lambda step: jax.numpy.where(step < warmup_steps, ramp(step),
                                            base_lr)
    return optax.join_schedules([ramp, after], [warmup_steps])


class EFLRScaleCallback(Callback):
    """Keep ErrorFeedback's carried error consistent with a changing
    learning rate: call `on_step` each step; when the schedule's LR
    changes it applies the reference's one-shot `prev_lr/new_lr` rescale
    to every EF state inside the optimizer state
    (ops.compressor.set_lr_scale; reference: the lr.s mmap written by the
    MXNet trainer, impl/vanilla_error_feedback.cc,
    mxnet/__init__.py:326-331 — here the schedule is known in-process, so
    no file plumbing).

        opt_state = cb.on_step(step, opt_state)   # before the train step
    """

    def __init__(self, schedule: optax.Schedule):
        self.schedule = schedule
        self._prev: Optional[float] = None

    def on_step(self, step: int, opt_state: PyTree) -> PyTree:
        from .ops.compressor import set_lr_scale
        lr = float(self.schedule(step))
        # Rescale only between positive LRs, and track the last NONZERO
        # lr: warmup schedules start at 0 (a 0/new_lr scale would zero the
        # carried EF error permanently — the scale one-shot resets after
        # the next compress), and a mid-training lr=0 step (cycle/restart
        # schedules) must not make the eventual positive->positive
        # transition forget the pre-zero scale.
        if (self._prev is not None and self._prev > 0 and lr > 0
                and lr != self._prev):
            opt_state = set_lr_scale(opt_state, self._prev / lr)
        if lr > 0:
            self._prev = lr
        return opt_state


def scaled_lr(base_lr: float, size: Optional[int] = None) -> float:
    """Linear LR scaling by world size (the reference multiplies lr by
    hvd.size() in its examples)."""
    if size is None:
        from .common.api import size as _size
        size = _size()
    return base_lr * size
