"""TensorFlow plugin: the reference's TF API surface on the TPU framework.

Mirrors byteps.tensorflow (reference: byteps/tensorflow/__init__.py:40-81,
110-182, 280-415): `init/shutdown`, `rank/size/local_rank/local_size`,
`push_pull`, `broadcast_variables`, `broadcast_global_variables`,
`BroadcastGlobalVariablesHook`, `DistributedOptimizer` (tf.compat.v1),
`DistributedGradientTape` — so TF training scripts written for the
reference port by changing the import.

Execution model (same stance as the torch plugin): TF tensors live on
host; communication rides the framework's eager push_pull (XLA
collectives across JAX processes, or the PS tier under
BYTEPS_TPU_PS_MODE).  Inside `tf.function` graphs the communication op is
a `tf.py_function` boundary — the TPU compute path for TF users is
tf.function on their side and JAX/XLA on the wire side, stitched at the
host.  The reference instead registers a custom C++ TF op
(tensorflow/ops.cc:87-98); a py_function keeps the same graph-insertion
point without binding against TF's C++ ABI.
"""

from __future__ import annotations

import threading
import weakref
from typing import Iterable, List, Optional

import numpy as np
import tensorflow as tf

from ..common import api as _api
from ..ops.compression import Compression

# Lifecycle / topology re-exports (reference: common/__init__.py:52-139)
init = _api.init
shutdown = _api.shutdown
suspend = _api.suspend
resume = _api.resume
rank = _api.rank
size = _api.size
local_rank = _api.local_rank
local_size = _api.local_size
declare = _api.declare
get_pushpull_speed = _api.get_pushpull_speed

_name_lock = threading.Lock()
# Unnamed symbolic tensors get per-GRAPH indices keyed by the graph object:
# a retrace of the same tf.function (new input signature -> fresh FuncGraph,
# same graph name) replays the same index sequence and re-derives the SAME
# tensor names, instead of minting fresh declared keys — and, in PS mode,
# fresh server-side stores — on every retrace.  Distinct same-named
# functions can still collide; pass name= explicitly where that matters.
_graph_counters = weakref.WeakKeyDictionary()


def _auto_name(scope: str, tensor) -> str:
    """Per-call-site tensor name.  The reference derives it from the TF
    graph scope (tensorflow/ops.py:109-134).  Symbolic tensors use their
    stable graph name; unnamed ones fall back to a per-graph counter (see
    above).  In EAGER mode auto-naming would declare a new key every call,
    so an explicit name is required (same contract as Horovod's eager
    allreduce)."""
    tname = getattr(tensor, "name", None) if not hasattr(tensor, "numpy") \
        else None  # EagerTensor.name raises; symbolic names are stable
    if tname:
        return f"{scope}byteps_push_pull_{str(tname).replace(':', '_')}"
    if tf.executing_eagerly():
        raise ValueError(
            "push_pull of an eager tensor requires an explicit name= "
            "(auto-naming would declare a new key every call)")
    graph = getattr(tensor, "graph", None)
    with _name_lock:
        if graph is not None:
            idx = _graph_counters.get(graph, 0)
            _graph_counters[graph] = idx + 1
            gname = str(getattr(graph, "name", "graph")).replace(":", "_")
            return f"{scope}byteps_push_pull_{gname}_{idx}"
        # No graph handle at all: last-resort process counter (documented
        # retrace hazard, docs/frameworks.md).
        _graph_counters[_auto_name] = _graph_counters.get(_auto_name, 0) + 1
        return f"{scope}byteps_push_pull_anon_{_graph_counters[_auto_name]}"


def push_pull(tensor, scope: str = "", average: bool = True,
              name: Optional[str] = None, priority: int = 0,
              compression=Compression.none):
    """Sum (or average) `tensor` across workers
    (reference: tensorflow/__init__.py:40-81).

    Works on eager tensors directly and inside tf.function via a
    py_function boundary.
    """
    import jax.numpy as jnp

    if name is None:
        name = _auto_name(scope, tensor)

    def _eager(t):
        out = _api.push_pull(jnp.asarray(t.numpy()), name=name,
                             average=average, priority=priority,
                             compression=compression)
        return tf.convert_to_tensor(np.asarray(out), dtype=t.dtype)

    # Eager tensors expose .numpy(); symbolic ones (inside tf.function
    # traces / functional graphs) don't and take the py_function boundary.
    if tf.executing_eagerly() and hasattr(tensor, "numpy"):
        return _eager(tf.convert_to_tensor(tensor))
    if tf.executing_eagerly() and not tf.is_tensor(tensor):
        return _eager(tf.convert_to_tensor(tensor))  # ndarray / python list
    out = tf.py_function(_eager, [tensor], Tout=tensor.dtype)
    out.set_shape(tensor.shape)
    return out


def push_pull_group(tensors, names, average: bool = True,
                    compression=Compression.none):
    """Sum/average a LIST of tensors across workers with ONE host
    boundary.

    The per-tensor `push_pull` pays a TF->JAX->TF crossing per gradient
    (the documented py_function trade-off); gradient lists are the common
    case, so this batches the whole list through one py_function call AND
    one batched collective (api.push_pull_tree — the reference's DDP
    gradient-batching stance, torch/parallel/distributed.py:235-243).
    `None` entries pass through.
    """
    import jax.numpy as jnp

    idx = [i for i, t in enumerate(tensors) if t is not None]
    if not idx:
        return list(tensors)
    live = [tensors[i] for i in idx]
    live_names = [names[i] for i in idx]

    def _eager_group(*ts):
        # One batched collective for the whole list (api.push_pull_tree):
        # a single wire transfer replaces the per-tensor dispatch loop, so
        # there are no partially-dispatched handles to drain on error.
        # The tree is a LIST (not a name-keyed dict): duplicate entries in
        # `names` must stay independent tensors, not collapse to one key.
        import hashlib
        tree = [jnp.asarray(t.numpy()) for t in ts]
        sig = hashlib.md5("|".join(live_names).encode()).hexdigest()[:12]
        out = _api.push_pull_tree(tree, name=f"byteps_tpu.tf_group.{sig}",
                                  average=average, compression=compression,
                                  leaf_names=live_names)
        return [tf.convert_to_tensor(np.asarray(o), dtype=t.dtype)
                for o, t in zip(out, ts)]

    # Eager tensors always expose .numpy() after convert_to_tensor, so the
    # eager mode calls _eager_group directly; py_function is the non-eager
    # trace boundary only (mirrors single-tensor push_pull's split).
    if tf.executing_eagerly():
        live = [tf.convert_to_tensor(t) for t in live]
        outs = _eager_group(*live)
    else:
        outs = tf.py_function(_eager_group, live,
                              Tout=[t.dtype for t in live])
        for o, t in zip(outs, live):
            o.set_shape(t.shape)
    merged = list(tensors)
    for i, o in zip(idx, outs):
        merged[i] = o
    return merged


def broadcast_variables(variables: Iterable[tf.Variable], root_rank: int = 0,
                        scope: str = "") -> None:
    """Assign every worker rank `root_rank`'s values
    (reference: tensorflow/__init__.py:110-130).  All variables travel in
    ONE tree broadcast — a single host round-trip."""
    import jax.numpy as jnp
    del scope
    vs = list(variables)
    if not vs:
        return
    tree = {str(i): jnp.asarray(v.numpy()) for i, v in enumerate(vs)}
    out = _api.broadcast_parameters(tree, root_rank)
    for i, v in enumerate(vs):
        v.assign(tf.convert_to_tensor(np.asarray(out[str(i)]),
                                      dtype=v.dtype))


def broadcast_global_variables(root_rank: int = 0) -> None:
    """TF1 global-collection analog (reference:
    tensorflow/__init__.py:93-108); in TF2 eager there is no globals
    collection, so this broadcasts tf.compat.v1 global variables when a
    graph exists and raises otherwise."""
    gvars = tf.compat.v1.global_variables()
    if not gvars:
        raise ValueError(
            "broadcast_global_variables found no global variables; in TF2 "
            "use broadcast_variables(model.variables, root_rank)")
    broadcast_variables(gvars, root_rank)


class BroadcastGlobalVariablesHook(tf.compat.v1.train.SessionRunHook):
    """TF1 MonitoredSession hook that broadcasts global variables once
    after session creation (reference: tensorflow/__init__.py:133-182)."""

    def __init__(self, root_rank: int = 0, device: str = ""):
        super().__init__()
        self.root_rank = root_rank
        self.bcast_op = None
        del device  # device pinning is XLA's job here

    def begin(self):
        gvars = tf.compat.v1.global_variables()
        self._vars = gvars

    def after_create_session(self, session, coord):
        del session, coord
        broadcast_variables(self._vars, self.root_rank)


def DistributedOptimizer(optimizer, name: Optional[str] = None,
                         use_locking: bool = False,
                         compression=Compression.none,
                         sparse_as_dense: bool = False,
                         backward_passes_per_step: int = 1):
    """Wrap a tf.compat.v1.train.Optimizer so gradients are push_pull-
    averaged before apply (reference: tensorflow/__init__.py:280-340).

    For Keras 3 optimizers use byteps_tpu.tensorflow.keras.
    DistributedOptimizer instead.
    """
    if not isinstance(optimizer, tf.compat.v1.train.Optimizer):
        raise TypeError(
            f"DistributedOptimizer wraps tf.compat.v1.train.Optimizer; got "
            f"{type(optimizer)} (Keras optimizers: use "
            "byteps_tpu.tensorflow.keras.DistributedOptimizer)")

    class _Dist(tf.compat.v1.train.Optimizer):
        def __init__(self):
            self._opt = optimizer
            self._compression = compression
            self._bpps = backward_passes_per_step
            super().__init__(name=name or
                             f"Distributed{type(optimizer).__name__}",
                             use_locking=use_locking)

        def compute_gradients(self, *args, **kwargs):
            gvs = self._opt.compute_gradients(*args, **kwargs)
            grads, names = [], []
            for g, v in gvs:
                if g is not None and sparse_as_dense \
                        and isinstance(g, tf.IndexedSlices):
                    g = tf.convert_to_tensor(g)
                grads.append(g)
                names.append(f"Gradient.{v.name.replace(':', '_')}")
            merged = push_pull_group(grads, names, average=True,
                                     compression=self._compression)
            return [(m, v) for m, (_, v) in zip(merged, gvs)]

        # Delegate everything apply-side to the wrapped optimizer.
        def apply_gradients(self, *args, **kwargs):
            return self._opt.apply_gradients(*args, **kwargs)

        def get_slot(self, *args, **kwargs):
            return self._opt.get_slot(*args, **kwargs)

        def get_slot_names(self, *args, **kwargs):
            return self._opt.get_slot_names(*args, **kwargs)

        def variables(self, *args, **kwargs):
            return self._opt.variables(*args, **kwargs)

    return _Dist()


class DistributedGradientTape(object):
    """Wrap tf.GradientTape so gradient() returns push_pull-averaged
    gradients (reference: tensorflow/__init__.py:341-415)."""

    def __init__(self, gradtape: tf.GradientTape,
                 compression=Compression.none,
                 sparse_as_dense: bool = False):
        self._tape = gradtape
        self._compression = compression
        self._sparse_as_dense = sparse_as_dense

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def __getattr__(self, item):
        return getattr(self._tape, item)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources,
                                    output_gradients=output_gradients)
        flat_sources = tf.nest.flatten(sources)
        flat, names = [], []
        for i, (g, s) in enumerate(zip(tf.nest.flatten(grads),
                                       flat_sources)):
            if g is not None and self._sparse_as_dense \
                    and isinstance(g, tf.IndexedSlices):
                g = tf.convert_to_tensor(g)
            flat.append(g)
            sname = getattr(s, "name", f"src_{i}").replace(":", "_")
            names.append(f"Gradient.{sname}")
        merged = push_pull_group(flat, names, average=True,
                                 compression=self._compression)
        return tf.nest.pack_sequence_as(grads, merged)
