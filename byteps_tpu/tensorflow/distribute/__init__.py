"""tf.distribute-shaped strategy whose cross-replica reduction rides byteps.

The reference forks TF's MirroredStrategy + CollectiveAllReduce (1,651 LoC of
TF internals) so that cross-device reduction routes into `_push_pull`
(reference: byteps/tensorflow/distribute/mirrored_strategy.py,
cross_device_ops.py:585-627) with chunked gradient packing
(cross_device_ops.py:251-296).  The TPU-native build keeps the *behavioral*
contract without the fork:

  - `BytepsCrossDeviceOps.batch_reduce` packs tensors into `num_packs`
    chunks, one framework push_pull per chunk (fewer, larger transfers —
    the reference's pack-then-all-reduce), and unpacks bit-exactly;
  - `MirroredStrategy.scope()` broadcasts every variable created inside it
    from root rank (the fork's _create_variable + broadcast behavior);
  - `strategy.reduce / extended.batch_reduce_to` route into the cross-device
    ops, so custom training loops written against the tf.distribute surface
    port directly;
  - one process == one replica (the JAX single-controller stance,
    common/api.py): `run()` invokes the fn directly and
    `num_replicas_in_sync == size()`.

Keras `model.fit` composes as: build + compile inside `strategy.scope()`
with `strategy.distribute_optimizer(opt)` — variables broadcast at
creation, gradients reduce through push_pull.
"""

from __future__ import annotations

import contextlib
from typing import Iterable, List, Optional, Sequence

import numpy as np
import tensorflow as tf

from .. import broadcast_variables, push_pull
from ...common import api as _api


def _norm_reduce_op(reduce_op) -> str:
    s = str(reduce_op).lower()
    if "mean" in s:
        return "mean"
    if "sum" in s:
        return "sum"
    raise ValueError(f"unsupported reduce op {reduce_op!r}; use SUM or MEAN")


class BytepsCrossDeviceOps:
    """Cross-replica reduction via framework push_pull with chunked packing
    (the CollectiveAllReduce analog, reference:
    cross_device_ops.py:585-627, 251-296).

    num_packs=0 disables packing (one push_pull per tensor); otherwise the
    tensor list is split into `num_packs` chunks — first n-1 chunks get
    len//num_packs tensors, the last chunk the leftover, matching the
    reference's _make_gradient_chunks split.
    """

    def __init__(self, num_packs: int = 1, scope: str = "CrossDeviceOps"):
        if num_packs < 0:
            raise ValueError(
                f"num_packs must be >= 0, got {num_packs}")
        self.num_packs = num_packs
        self._scope = scope

    # -- packing ------------------------------------------------------------
    def _chunks(self, values: Sequence) -> List[List[int]]:
        n = len(values)
        if self.num_packs == 0 or n < self.num_packs:
            return [[i] for i in range(n)]
        # First n-1 packs get n//num_packs tensors each, the last pack the
        # leftover (reference: cross_device_ops.py:251-296).
        chunk = n // self.num_packs
        split = chunk * (self.num_packs - 1)
        out = [list(range(s, s + chunk)) for s in range(0, split, chunk)]
        out.append(list(range(split, n)))
        return out

    @staticmethod
    def _static_size(t) -> Optional[int]:
        """Element count when the static shape is fully defined, else None
        (dynamic dims appear under tf.function with None in the
        input_signature / drop_remainder=False datasets)."""
        if t.shape.rank is None or not t.shape.is_fully_defined():
            return None
        return int(np.prod(t.shape)) if t.shape.rank else 1

    def reduce(self, reduce_op, value, destinations=None,
               name: Optional[str] = None):
        """Reduce one tensor across workers (reference:
        cross_device_ops.py reduce_implementation -> _push_pull).

        `name` keys the communication (and, in PS mode, the server store);
        required for distinct call sites with dynamic shapes — see
        batch_reduce."""
        del destinations  # one replica per process: result lives everywhere
        op = _norm_reduce_op(reduce_op)
        value = tf.convert_to_tensor(value)
        n = self._static_size(value)
        name = name or f"{self._scope}.reduce.{'dyn' if n is None else n}"
        return push_pull(value, average=(op == "mean"), name=name)

    def batch_reduce(self, reduce_op, values: Sequence,
                     destinations=None, name: Optional[str] = None) -> List:
        """Reduce a list of tensors, packed into num_packs transfers.
        Handles dynamic (None) dims by falling back to graph-time sizes.

        Auto-derived pack names carry the total element count so
        differently-shaped call sites get distinct keys; with DYNAMIC dims
        the count is unknown at trace time, so two call sites whose
        dynamic packs differ in byte size would collide on one key — in PS
        mode that re-INITs the server store per size change and can fail
        a concurrent pull.  Pass a distinct `name` per call site there."""
        del destinations
        op = _norm_reduce_op(reduce_op)
        values = list(values)
        if not values:
            return []
        out: List = [None] * len(values)
        for ci, idxs in enumerate(self._chunks(values)):
            tensors = [tf.convert_to_tensor(values[i]) for i in idxs]
            sizes = [self._static_size(t) for t in tensors]
            total = None if any(s is None for s in sizes) else sum(sizes)
            if len(tensors) == 1:
                flatpack = tf.reshape(tensors[0], [-1])
            else:
                flatpack = tf.concat(
                    [tf.reshape(t, [-1]) for t in tensors], axis=0)
            pack_name = (f"{name}.pack{ci}" if name else
                         f"{self._scope}.pack{ci}."
                         f"{'dyn' if total is None else total}")
            reduced = push_pull(flatpack, average=(op == "mean"),
                                name=pack_name)
            off = 0
            for i, t, n in zip(idxs, tensors, sizes):
                if n is None:
                    piece = tf.slice(reduced, [off], [tf.size(t)])
                    piece = tf.reshape(piece, tf.shape(t))
                    piece.set_shape(t.shape)  # keep known static dims
                    out[i] = piece
                    off = off + tf.size(t)
                else:
                    out[i] = tf.reshape(tf.slice(reduced, [off], [n]),
                                        t.shape)
                    off = off + n
        return out


class _Extended:
    """The strategy.extended face (StrategyExtended surface subset)."""

    def __init__(self, xops: BytepsCrossDeviceOps):
        self._xops = xops

    def reduce_to(self, reduce_op, value, destinations=None):
        return self._xops.reduce(reduce_op, value, destinations)

    def batch_reduce_to(self, reduce_op, value_destination_pairs):
        pairs = list(value_destination_pairs)
        values = [v for v, _d in pairs]
        return self._xops.batch_reduce(reduce_op, values)


class MirroredStrategy:
    """Strategy-shaped wrapper: tf.distribute.MirroredStrategy's surface,
    byteps push_pull underneath (reference:
    tensorflow/distribute/mirrored_strategy.py).

    One replica per worker process; replicas synchronize through the
    framework's communication tier (XLA collectives or the PS servers),
    never through TF's collective runtime.
    """

    def __init__(self, num_packs: int = 1, root_rank: int = 0):
        self.cross_device_ops = BytepsCrossDeviceOps(num_packs=num_packs)
        self.extended = _Extended(self.cross_device_ops)
        self.root_rank = root_rank
        self.broadcast_count = 0  # introspection/testing

    # -- topology -----------------------------------------------------------
    @property
    def num_replicas_in_sync(self) -> int:
        return _api.size()

    # -- variable lifecycle -------------------------------------------------
    @contextlib.contextmanager
    def scope(self):
        """Variables created inside adopt root_rank's initial values —
        the fork's create-then-broadcast behavior
        (reference: mirrored_strategy.py variable creation path)."""
        deferred: List = []

        def creator(next_creator, **kwargs):
            v = next_creator(**kwargs)
            if tf.executing_eagerly():
                broadcast_variables([v], self.root_rank)
                self.broadcast_count += 1
            else:
                deferred.append(v)  # created under a trace: broadcast after
            return v

        with tf.variable_creator_scope(creator):
            yield self
        if deferred:
            broadcast_variables(deferred, self.root_rank)
            self.broadcast_count += len(deferred)

    # -- execution ----------------------------------------------------------
    def run(self, fn, args=(), kwargs=None):
        """One local replica per process: run fn directly (the per-GPU
        fan-out of the reference fork collapses, mirroring common/api.py's
        single-controller stance)."""
        return fn(*args, **(kwargs or {}))

    def reduce(self, reduce_op, value, axis=None):
        if axis is not None:
            value = tf.reduce_sum(value, axis=axis) \
                if _norm_reduce_op(reduce_op) == "sum" \
                else tf.reduce_mean(value, axis=axis)
        return self.cross_device_ops.reduce(reduce_op, value)

    def gradient_all_reduce(self, grads: Iterable,
                            average: bool = True) -> List:
        """Convenience for custom loops: packed mean/sum of a grad list."""
        return self.cross_device_ops.batch_reduce(
            "mean" if average else "sum", list(grads))

    def distribute_optimizer(self, optimizer, compression=None):
        """Wrap a Keras-3 optimizer so fit() reduces gradients through this
        strategy's communication tier."""
        from ..keras import DistributedOptimizer
        from ...ops.compression import Compression
        return DistributedOptimizer(
            optimizer, compression=compression or Compression.none)

    def experimental_distribute_dataset(self, dataset: tf.data.Dataset):
        """Each worker reads its own shard (the input-pipeline contract of
        the reference fork's per-worker datasets)."""
        return dataset.shard(num_shards=_api.size(), index=_api.rank())
