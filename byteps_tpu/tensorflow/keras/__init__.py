"""Keras plugin: DistributedOptimizer + callbacks for Keras 3 on TF.

The reference wraps Keras 2 optimizers by overriding get_gradients in a
dynamic subclass (reference: byteps/_keras/__init__.py:20-83) and ships a
callback suite (reference: byteps/_keras/callbacks.py:23-196).  Keras 3
moved the override point: Model.train_step calls
`optimizer.apply_gradients(zip(grads, weights))`, so the distributed
wrapper intercepts there — gradients are push_pull-averaged across
workers before the inner optimizer applies them.
"""

from __future__ import annotations

from typing import Optional

import keras
import numpy as np

from .. import push_pull, push_pull_group, broadcast_variables
from ...common import api as _api
from ...ops.compression import Compression

init = _api.init
shutdown = _api.shutdown
rank = _api.rank
size = _api.size
local_rank = _api.local_rank
local_size = _api.local_size


def DistributedOptimizer(optimizer: keras.optimizers.Optimizer,
                         compression=Compression.none):
    """Clone `optimizer` into a dynamic subclass whose apply_gradients
    push_pull-averages gradients first (the Keras-3 analog of the
    reference's get_gradients override, _keras/__init__.py:33-66)."""
    cls = optimizer.__class__

    class _Distributed(cls):
        _bps_compression = compression

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            gvs = list(grads_and_vars)
            grads, names = [], []
            for i, (g, v) in enumerate(gvs):
                grads.append(g)
                # Keras-3 variable .name is NOT unique ("kernel"/"bias" on
                # every Dense); .path is ("sequential/dense_1/kernel").
                vname = (getattr(v, "path", None)
                         or getattr(v, "name", None) or f"var_{i}")
                names.append(
                    f"Gradient.{str(vname).replace(':', '_')}")
            # One host boundary for the whole gradient list.
            merged = push_pull_group(grads, names, average=True,
                                     compression=self._bps_compression)
            synced = [(m, v) for m, (_, v) in zip(merged, gvs)]
            return super().apply_gradients(synced, *args, **kwargs)

    _Distributed.__name__ = "Distributed" + cls.__name__
    return _Distributed.from_config(optimizer.get_config())


class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
    """Broadcast model + optimizer variables from root_rank at the start of
    training (reference: _keras/callbacks.py:23-49)."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self._done = False

    def on_batch_end(self, batch, logs=None):
        # After batch 0, not before: Keras 3 builds optimizer slot
        # variables lazily on first apply, so broadcasting earlier would
        # silently skip optimizer state (rank-divergent Adam moments etc.).
        # Rank 0's post-step values win, same contract as the reference.
        if self._done:
            return
        broadcast_variables(self.model.variables, self.root_rank)
        opt = getattr(self.model, "optimizer", None)
        if opt is not None and getattr(opt, "variables", None):
            # keras3 exposes optimizer.variables as a property list
            vars = opt.variables if isinstance(opt.variables, list) \
                else opt.variables()
            broadcast_variables([v for v in vars if hasattr(v, "assign")],
                                self.root_rank)
        self._done = True


class MetricAverageCallback(keras.callbacks.Callback):
    """Average epoch metrics across workers before they reach other
    callbacks/logs (reference: _keras/callbacks.py:52-91)."""

    def on_epoch_end(self, epoch, logs=None):
        import jax.numpy as jnp
        if not logs or _api.size() == 1:
            return
        for k, v in list(logs.items()):
            if isinstance(v, (int, float, np.floating)):
                logs[k] = float(_api.push_pull(
                    jnp.float32(v), name=f"metric.{k}", average=True))


class LearningRateWarmupCallback(keras.callbacks.Callback):
    """Ramp lr from base_lr*init_factor to base_lr over warmup_epochs
    (reference: _keras/callbacks.py:144-196, the 'Accurate, Large
    Minibatch SGD' gradual-warmup recipe)."""

    def __init__(self, warmup_epochs: int = 5, momentum_correction=True,
                 steps_per_epoch: Optional[int] = None, verbose: int = 0,
                 initial_lr: Optional[float] = None):
        super().__init__()
        self.warmup_epochs = warmup_epochs
        self.steps_per_epoch = steps_per_epoch
        self.verbose = verbose
        self.initial_lr = initial_lr
        self._current_epoch = 0
        del momentum_correction  # optax-style handling not needed here

    def _base_lr(self):
        if self.initial_lr is not None:
            return self.initial_lr
        return float(keras.ops.convert_to_numpy(
            self.model.optimizer.learning_rate))

    def on_train_begin(self, logs=None):
        self._base = self._base_lr()

    def on_epoch_begin(self, epoch, logs=None):
        self._current_epoch = epoch

    def on_batch_begin(self, batch, logs=None):
        if self._current_epoch >= self.warmup_epochs:
            return
        spe = self.steps_per_epoch or self.params.get("steps") or 100
        progress = (self._current_epoch * spe + batch) / (
            self.warmup_epochs * spe)
        factor = 1.0 / 3 + (1 - 1.0 / 3) * min(progress, 1.0)
        self.model.optimizer.learning_rate.assign(self._base * factor)

    def on_train_end(self, logs=None):
        self.model.optimizer.learning_rate.assign(self._base)
