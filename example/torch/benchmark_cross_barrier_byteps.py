"""Cross-barrier benchmark for the torch plugin.

Torch counterpart of the reference's
example/pytorch/benchmark_cross_barrier_byteps.py: train the same
synthetic model with the plain DistributedOptimizer (global sync barrier
before step()) and with CrossBarrier (per-parameter updates applied as
each gradient's push_pull completes; the next forward blocks per LAYER),
and report steps/sec for both.  On a real multi-worker wire the gap is
the communication time hidden behind the next step's forward
(reference: docs/cross-barrier.md, ByteScheduler).

Run:
    python example/torch/benchmark_cross_barrier_byteps.py --steps 30
"""

import argparse
import time

import torch
import torch.nn.functional as F

import byteps_tpu.torch as bps


def make_model(width: int, depth: int) -> torch.nn.Module:
    layers = [l for _ in range(depth)
              for l in (torch.nn.Linear(width, width), torch.nn.ReLU())]
    return torch.nn.Sequential(*layers, torch.nn.Linear(width, 10))


def run(steps: int, width: int, depth: int, cross_barrier: bool) -> float:
    torch.manual_seed(0)
    model = make_model(width, depth)
    inner = torch.optim.SGD(model.parameters(), lr=0.01)
    if cross_barrier:
        opt = bps.CrossBarrier(model, inner,
                               named_parameters=model.named_parameters())
    else:
        opt = bps.DistributedOptimizer(
            inner, named_parameters=model.named_parameters())
    x = torch.randn(64, width)
    y = torch.randint(0, 10, (64,))
    # warmup (first dispatch declares keys / compiles)
    F.cross_entropy(model(x), y).backward()
    opt.step()
    t0 = time.perf_counter()
    for _ in range(steps):
        if not cross_barrier:
            opt.zero_grad()
        F.cross_entropy(model(x), y).backward()
        opt.step()
    if cross_barrier:
        opt.synchronize()   # drain before the clock stops
        opt.close()
    dt = time.perf_counter() - t0
    return steps / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--width", type=int, default=256)
    ap.add_argument("--depth", type=int, default=6)
    args = ap.parse_args()

    bps.init()
    base = run(args.steps, args.width, args.depth, cross_barrier=False)
    xb = run(args.steps, args.width, args.depth, cross_barrier=True)
    print(f"rank {bps.rank()}/{bps.size()}: "
          f"baseline {base:.1f} steps/s, cross-barrier {xb:.1f} steps/s "
          f"({xb / base:.2f}x)")
    bps.shutdown()


if __name__ == "__main__":
    main()
