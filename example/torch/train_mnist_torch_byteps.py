"""Train an MNIST-style MLP with the PyTorch plugin.

The torch counterpart of example/jax/train_mnist_byteps.py, mirroring the
reference's example/pytorch/train_mnist_byteps.py shape: broadcast initial
state, wrap the optimizer in DistributedOptimizer (gradients are averaged
across workers through the framework's eager push_pull), train, report.

Uses a synthetic MNIST-like dataset so the example runs hermetically (no
downloads); swap in torchvision.datasets.MNIST for the real thing.

Run (single worker):
    python example/torch/train_mnist_torch_byteps.py --epochs 2
Async PS mode (reference: BYTEPS_ENABLE_ASYNC):
    BYTEPS_TPU_PS_MODE=1 BYTEPS_ENABLE_ASYNC=1 ... bpslaunch ...
"""

import argparse

import numpy as np
import torch
import torch.nn.functional as F

import byteps_tpu.torch as bps


class Net(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = torch.nn.Linear(784, 128)
        self.fc2 = torch.nn.Linear(128, 10)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x.flatten(1))))


def synthetic_mnist(n=4096, seed=0):
    """Class-conditioned Gaussian blobs in pixel space: learnable, fast.

    The class prototypes come from a FIXED seed so every worker sees the
    same task; only the per-worker sample draw varies with `seed`.
    """
    protos = np.random.RandomState(0).randn(10, 784).astype(np.float32)
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, size=n)
    x = protos[y] + 0.5 * rng.randn(n, 784).astype(np.float32)
    return (torch.from_numpy(x.reshape(n, 1, 28, 28)),
            torch.from_numpy(y.astype(np.int64)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()

    bps.init()
    torch.manual_seed(42 + bps.rank())

    model = Net()
    opt = torch.optim.SGD(model.parameters(), lr=args.lr)
    opt = bps.DistributedOptimizer(
        opt, named_parameters=model.named_parameters())

    # Every worker starts from rank 0's weights (reference pattern).
    bps.broadcast_parameters(model.state_dict(), root_rank=0)

    x, y = synthetic_mnist(seed=bps.rank())  # each worker gets its shard
    n = x.shape[0]
    for epoch in range(args.epochs):
        perm = torch.randperm(n)
        total, correct, loss_sum = 0, 0, 0.0
        for i in range(0, n, args.batch_size):
            idx = perm[i:i + args.batch_size]
            xb, yb = x[idx], y[idx]
            opt.zero_grad()
            logits = model(xb)
            loss = F.cross_entropy(logits, yb)
            loss.backward()
            opt.step()
            loss_sum += float(loss) * len(idx)
            correct += int((logits.argmax(1) == yb).sum())
            total += len(idx)
        print(f"rank {bps.rank()}/{bps.size()} epoch {epoch}: "
              f"loss={loss_sum / total:.4f} acc={correct / total:.3f}")
    bps.shutdown()


if __name__ == "__main__":
    main()
