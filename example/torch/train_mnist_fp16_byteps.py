"""Half-precision torch training with fp32 master weights.

The reference's imagenet18 recipe (reference:
byteps/misc/imagenet18/__init__.py:39-330 `_HalfPrecisionDistributedOptimizer`)
on byteps_tpu: model in fp16, gradients cross the wire compressed, an fp32
master copy takes the optimizer updates, masters cast back after each step.

Run (synthetic MNIST-shaped data, works on CPU):
    python example/torch/train_mnist_fp16_byteps.py --steps 30
"""

import argparse

import torch

import byteps_tpu.torch as bps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--loss-scale", default="dynamic",
                    help='"dynamic" or a float like 1024')
    args = ap.parse_args()

    bps.init()
    torch.manual_seed(bps.rank())

    model = torch.nn.Sequential(
        torch.nn.Flatten(),
        torch.nn.Linear(28 * 28, 128), torch.nn.ReLU(),
        torch.nn.Linear(128, 10),
    ).to(torch.float16)

    scale = args.loss_scale if args.loss_scale == "dynamic" \
        else float(args.loss_scale)
    opt = bps.HalfPrecisionDistributedOptimizer(
        model, lambda ps: torch.optim.SGD(ps, lr=args.lr),
        loss_scale=scale)
    bps.broadcast_fp16_parameters(opt, root_rank=0)

    gen = torch.Generator().manual_seed(0)  # same data on every worker
    # Learnable synthetic task: labels from a fixed random linear probe,
    # fixed batch (a convergence smoke, like the reference MNIST demos).
    probe = torch.randn(28 * 28, 10, generator=gen)
    x = torch.randn(args.batch_size, 28, 28, generator=gen).half()
    y = (x.float().flatten(1) @ probe).argmax(-1)
    first_loss = last_loss = None
    for step in range(args.steps):
        opt.zero_grad()
        logits = model(x).float()
        loss = torch.nn.functional.cross_entropy(logits, y)
        opt.scale_loss(loss).backward()
        opt.step()
        last_loss = float(loss.detach())
        if first_loss is None:
            first_loss = last_loss
        if step % 10 == 0 or step == args.steps - 1:
            acc = (logits.argmax(-1) == y).float().mean()
            print(f"step {step}: loss={last_loss:.4f} "
                  f"acc={float(acc):.3f} scale={opt.loss_scale:.0f} "
                  f"skipped={opt.steps_skipped}")
    if args.steps >= 20:
        assert last_loss < first_loss, (first_loss, last_loss)
    print("fp16 training done")
    bps.shutdown()


if __name__ == "__main__":
    main()
