"""Synthetic-data throughput benchmark, mirroring the reference benchmark
CLI (reference: example/pytorch/benchmark_byteps.py — prints img/sec or
tokens/sec mean+-stddev over timed iterations).

  python example/jax/benchmark_byteps.py --model resnet50 --num-iters 10
  python example/jax/benchmark_byteps.py --model bert_large --profiler
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import byteps_tpu as bps
from byteps_tpu import models
from byteps_tpu.models import transformer as tfm


def build(args, mesh):
    if args.model in tfm.CONFIGS:
        cfg = tfm.get_config(args.model, causal=True)
        params = tfm.init_params(jax.random.key(0), cfg)
        toks, tgts = tfm.synthetic_batch(
            jax.random.key(1), args.batch_size, args.seq_len, cfg)
        loss = lambda p, b: tfm.loss_fn(p, b, cfg)
        batch = (toks, tgts)
        unit = "tokens"
        per_batch = args.batch_size * args.seq_len
    else:
        model = models.create_cnn(args.model, num_classes=1000)
        x = jnp.ones((args.batch_size, args.image_size, args.image_size, 3))
        params = model.init(jax.random.key(0), x, train=False)
        labels = jnp.zeros((args.batch_size,), jnp.int32)
        loss = models.cnn_loss_fn(model)
        batch = (x, labels)
        unit = "imgs"
        per_batch = args.batch_size
    opt = bps.DistributedOptimizer(optax.sgd(0.01))
    step = bps.build_train_step(loss, opt, mesh, donate=False,
                                accum_steps=args.accum_steps)
    return step, params, opt.init(params), batch, unit, per_batch


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--seq-len", type=int, default=512)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--num-warmup", type=int, default=2)
    ap.add_argument("--num-iters", type=int, default=10)
    ap.add_argument("--accum-steps", type=int, default=1,
                    help="microbatches per step (gradient accumulation, "
                         "one all-reduce per step)")
    ap.add_argument("--profiler", action="store_true",
                    help="wrap timed iters in jax.profiler traces")
    ap.add_argument("--trace-dir", default="/tmp/byteps_tpu_profile")
    args = ap.parse_args()

    bps.init()
    mesh = bps.get_mesh()
    step, params, opt_state, batch, unit, per_batch = build(args, mesh)

    for _ in range(args.num_warmup):
        params, opt_state, loss = step(params, opt_state, batch)
        float(loss)

    if args.profiler:
        jax.profiler.start_trace(args.trace_dir)
    rates = []
    for _ in range(args.num_iters):
        t0 = time.perf_counter()
        params, opt_state, loss = step(params, opt_state, batch)
        float(loss)
        rates.append(per_batch / (time.perf_counter() - t0))
    if args.profiler:
        jax.profiler.stop_trace()
        print(f"profile written to {args.trace_dir}")

    rates = np.asarray(rates)
    print(f"{args.model}: {rates.mean():.1f} +- {rates.std():.1f} "
          f"{unit}/sec per worker "
          f"(total {rates.mean() * bps.size():.1f})")
    ts, speed = bps.get_pushpull_speed()
    print(f"push_pull speed: {speed:.2f} MB/s")
    bps.shutdown()


if __name__ == "__main__":
    main()
