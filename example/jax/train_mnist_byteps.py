"""MNIST MLP with byteps_tpu — the hello-world example.

Counterpart of the reference's per-framework MNIST examples
(reference: example/pytorch/train_mnist_byteps.py).  Uses a synthetic
MNIST-shaped dataset so it runs hermetically; swap `synthetic_mnist` for a
real loader in practice.

Run:  python example/jax/train_mnist_byteps.py [--epochs 3]
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import byteps_tpu as bps
from byteps_tpu import models


def synthetic_mnist(n=4096, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    y = (x @ w).argmax(-1).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(y)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--lr", type=float, default=1e-3)
    args = ap.parse_args()

    bps.init()
    mesh = bps.get_mesh()
    print(f"rank {bps.rank()}/{bps.size()}, devices {jax.device_count()}")

    params = models.init_mlp(jax.random.key(0))
    params = bps.broadcast_parameters(params)

    opt = bps.DistributedOptimizer(
        optax.adam(bps.callbacks.scaled_lr(args.lr)))
    opt_state = opt.init(params)
    step = bps.build_train_step(models.mlp_loss, opt, mesh)

    x, y = synthetic_mnist()
    nb = x.shape[0] // args.batch_size
    for epoch in range(args.epochs):
        for i in range(nb):
            sl = slice(i * args.batch_size, (i + 1) * args.batch_size)
            params, opt_state, loss = step(params, opt_state, (x[sl], y[sl]))
            bps.mark_step()
        acc = float(models.mlp.accuracy(params, (x, y)))
        print(f"epoch {epoch}: loss={float(loss):.4f} acc={acc:.3f}")

    bps.shutdown()


if __name__ == "__main__":
    main()
