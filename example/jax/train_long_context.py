"""Long-context training with ring attention over the sequence axis.

No reference counterpart (the reference scales batch, never sequence —
SURVEY §5); this shows byteps_tpu's first-class sequence parallelism: an
8-way sp mesh trains on sequences 8x longer than one device's attention
memory would allow.  The hybrid model shards activations [B, S/sp, D] and
rotates K/V blocks around the sp ring (ops/ring_attention.py).

  python example/jax/train_long_context.py --sp 8 --seq-len 2048

For single-chip long context (no sp mesh), --attn flash uses the Pallas
flash-attention kernel instead: the S x S logits never materialize
(ops/flash_attention.py; measured 1.6x over XLA dense at S=4096, see
docs/performance.md).
"""

import argparse

import jax
import jax.numpy as jnp
import optax

import byteps_tpu as bps
from byteps_tpu.models import hybrid


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sp", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=2048)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--attn", choices=["ring", "flash"], default="ring",
                    help="ring: sp-sharded ring attention; flash: Pallas "
                         "flash kernel on unsharded sequence (sp ignored)")
    args = ap.parse_args()

    bps.init()
    if args.attn == "flash":
        from byteps_tpu.models import transformer as tfm
        cfg = tfm.get_config("tiny", causal=True, attn_impl="flash",
                             max_seq_len=args.seq_len,
                             vocab_size=1024)
        mesh = bps.make_mesh()  # dp over all chips; S stays whole
        opt = bps.DistributedOptimizer(optax.adam(1e-3))
        step = bps.build_train_step(
            lambda p, b: tfm.loss_fn(p, b, cfg), opt, mesh, donate=False)
        params = tfm.init_params(jax.random.key(0), cfg)
        opt_state = opt.init(params)
        bsz = max(1, jax.device_count())
        toks, tgts = tfm.synthetic_batch(jax.random.key(1), bsz,
                                         args.seq_len, cfg)
        for i in range(args.steps):
            params, opt_state, loss = step(params, opt_state, (toks, tgts))
            print(f"step {i}: loss={float(loss):.4f} "
                  f"(flash, seq_len={args.seq_len})")
        bps.shutdown()
        return
    mesh = bps.make_mesh(sp=args.sp)
    cfg = hybrid.HybridConfig(vocab_size=1024, num_layers=2, d_model=64,
                              num_heads=4, d_ff=128,
                              max_seq_len=args.seq_len)
    opt = optax.adam(1e-3)
    step, init_fn = hybrid.build_hybrid_train_step(cfg, opt, mesh)
    params = init_fn(jax.random.key(0))
    opt_state = opt.init(params)

    toks = jax.random.randint(jax.random.key(1), (4, args.seq_len), 0,
                              cfg.vocab_size, jnp.int32)
    tgts = jnp.roll(toks, -1, axis=1)
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, (toks, tgts))
        print(f"step {i}: loss={float(loss):.4f} (seq_len={args.seq_len})")
    bps.shutdown()


if __name__ == "__main__":
    main()
