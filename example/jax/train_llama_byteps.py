"""Train a llama-class decoder (RMSNorm + SwiGLU + RoPE + GQA) under the
framework's DP path, optionally TP-sharded via GSPMD PartitionSpecs.

No reference counterpart (the reference's model zoo is CNNs + BERT via an
external repo); this shows the modern-LLM block riding the same machinery
as the BERT flagship: DistributedOptimizer + bucketed priority all-reduce
on the dp axis, Megatron-style column/row specs on the tp axis
(models/transformer.param_specs), flash attention via --attn flash.

  python example/jax/train_llama_byteps.py --steps 20
  python example/jax/train_llama_byteps.py --tp 2 --model llama_tiny
  python example/jax/train_llama_byteps.py --tp 2 --zero1   # ZeRO-1
  python example/jax/train_llama_byteps.py --fsdp           # ZeRO-3-style
"""

import argparse

import jax
import jax.numpy as jnp
import optax

import byteps_tpu as bps
from byteps_tpu.models import transformer as tfm
from byteps_tpu.parallel import sharded


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama_tiny",
                    help="any llama_* config name (see transformer.CONFIGS)")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel degree (GSPMD-sharded params)")
    ap.add_argument("--attn", choices=["dense", "flash"], default="dense")
    ap.add_argument("--zero1", action="store_true",
                    help="shard optimizer state over dp (GSPMD path; "
                         "Adam moments drop to 1/dp per chip)")
    ap.add_argument("--fsdp", action="store_true",
                    help="shard the params themselves over dp too "
                         "(ZeRO-3-style; params+grads+moments all 1/dp)")
    args = ap.parse_args()

    bps.init()
    cfg = tfm.get_config(args.model, attn_impl=args.attn)
    params = tfm.init_params(jax.random.key(0), cfg)

    # dp defaults to "the remaining devices" inside make_mesh.
    mesh = bps.make_mesh(tp=args.tp)

    def loss_f(p, b):
        return tfm.loss_fn(p, b, cfg)

    if args.tp > 1 or args.zero1 or args.fsdp:
        # GSPMD path: params stay column/row-sharded over 'tp' end to end
        # (build_train_step's shard_map replicates params — wrong tool
        # for TP); --zero1 additionally shards the Adam moments over 'dp'
        # (weight-update sharding — the state that OOMs first at scale);
        # --fsdp shards the params themselves over 'dp' as well, with the
        # optimizer state following the params' layout.
        specs = tfm.param_specs(cfg)
        if args.fsdp:
            specs = sharded.fsdp_param_specs(params, mesh,
                                             base_specs=specs)
        params = sharded.shard_params(params, mesh, specs)
        raw_opt = optax.adamw(3e-3)
        z_specs = (sharded.zero1_opt_specs(raw_opt, params, mesh, specs)
                   if args.zero1 else None)
        step = bps.build_sharded_train_step(
            loss_f, raw_opt, mesh, specs, zero1=args.zero1,
            zero1_specs=z_specs)
        if args.zero1:
            opt_state = sharded.zero1_init(raw_opt, params, mesh, specs,
                                           opt_specs=z_specs)
        elif args.fsdp:
            opt_state = sharded.fsdp_init(raw_opt, params, mesh, specs)
        else:
            opt_state = raw_opt.init(params)
    else:
        opt = bps.DistributedOptimizer(optax.adamw(3e-3))
        step = bps.build_train_step(loss_f, opt, mesh)
        opt_state = opt.init(params)

    toks, tgts = tfm.synthetic_batch(jax.random.key(1), args.batch_size,
                                     args.seq_len, cfg)
    first = last = None
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, (toks, tgts))
        last = float(loss)
        first = first if first is not None else last
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i} loss {last:.4f}", flush=True)
    print(f"final: first={first:.4f} last={last:.4f} "
          f"improved={last < first}")
    bps.shutdown()


if __name__ == "__main__":
    main()
