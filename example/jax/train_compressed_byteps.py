"""Gradient-compression training demo.

Counterpart of the reference's compression example
(reference: example/mxnet/train_gluon_imagenet_byteps_gc.py — onebit
compressor + error feedback + momentum configured by string kwargs).

  python example/jax/train_compressed_byteps.py --compressor onebit \
      --ef vanilla --momentum nesterov
  python example/jax/train_compressed_byteps.py --compressor randomk --k 64
"""

import argparse

import jax
import jax.numpy as jnp
import optax

import byteps_tpu as bps
from byteps_tpu import models
from byteps_tpu.ops import compressor as C


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--compressor", default="onebit",
                    choices=C.known_compressors())
    ap.add_argument("--ef", default="", help="'vanilla' to enable")
    ap.add_argument("--momentum", default="", help="'nesterov' to enable")
    ap.add_argument("--k", type=int, default=64)
    ap.add_argument("--steps", type=int, default=30)
    args = ap.parse_args()

    bps.init()
    mesh = bps.get_mesh()
    kwargs = {"compressor": args.compressor, "k": args.k}
    if args.ef:
        kwargs["ef"] = args.ef
    if args.momentum:
        kwargs["momentum"] = args.momentum
    comp = C.create(kwargs)

    params = models.init_mlp(jax.random.key(0), (64, 128, 10))
    tree = {"w": jnp.zeros(64 * 128 + 128 * 10)}
    print(f"compressor={kwargs} ratio~{C.compression_ratio(tree, comp):.1f}x")

    opt = bps.DistributedOptimizer(optax.sgd(0.1), inter_compressor=comp)
    step = bps.build_train_step(models.mlp_loss, opt, mesh)
    opt_state = opt.init(params)

    x = jax.random.normal(jax.random.key(1), (512, 64))
    y = (x.sum(-1) > 0).astype(jnp.int32)
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, (x, y))
        if i % 5 == 0:
            print(f"step {i}: loss={float(loss):.4f}")
    bps.shutdown()


if __name__ == "__main__":
    main()
