"""Elastic suspend/resume demo.

Counterpart of the reference's elastic example
(reference: example/pytorch/elastic_benchmark_byteps.py:124-133 — training
suspends, the cluster is resized, training resumes with stable tensor
keys).

  python example/jax/elastic_benchmark_byteps.py
"""

import jax
import jax.numpy as jnp
import optax

import byteps_tpu as bps
from byteps_tpu import models


def train_steps(params, opt_state, step, n, x, y):
    for _ in range(n):
        params, opt_state, loss = step(params, opt_state, (x, y))
    return params, opt_state, float(loss)


def main():
    bps.init()
    mesh = bps.get_mesh()
    params = models.init_mlp(jax.random.key(0), (32, 64, 4))
    opt = bps.DistributedOptimizer(optax.sgd(0.1))
    step = bps.build_train_step(models.mlp_loss, opt, mesh)
    opt_state = opt.init(params)
    x = jax.random.normal(jax.random.key(1), (256, 32))
    y = (x.sum(-1) > 0).astype(jnp.int32)

    # declare some tensors so the registry has state worth preserving
    bps.declare("Gradient.w0")
    bps.declare("Gradient.b0")

    params, opt_state, loss = train_steps(params, opt_state, step, 5, x, y)
    print(f"phase 1 done: loss={loss:.4f}, declared={bps.declared_key('Gradient.b0')}")

    # --- elastic suspend: tear down comm, keep registry -------------------
    bps.suspend()
    # (a real deployment would wait for the new cluster size here)
    bps.resume(num_workers=1, num_servers=0)

    # keys survive resume in original order (reference: operations.cc:107-119)
    assert bps.declared_key("Gradient.w0") == 0
    assert bps.declared_key("Gradient.b0") == 1

    params, opt_state, loss = train_steps(params, opt_state, step, 5, x, y)
    print(f"phase 2 done after resume: loss={loss:.4f}")
    bps.shutdown()


if __name__ == "__main__":
    main()
