"""Hybrid-parallel training demo: dp x tp x sp x pp x ep in one step.

No reference counterpart — the reference is DP-only (SURVEY §2.6); this
example shows the TPU-native extension.  On an 8-device host:

  python example/jax/train_hybrid_parallel.py --pp 2 --dp 2 --tp 2
  python example/jax/train_hybrid_parallel.py --ep 4 --dp 2 --experts 8
"""

import argparse

import jax
import jax.numpy as jnp
import optax

import byteps_tpu as bps
from byteps_tpu.models import hybrid


def main():
    ap = argparse.ArgumentParser()
    for ax in ("dp", "tp", "sp", "pp", "ep"):
        ap.add_argument(f"--{ax}", type=int, default=1)
    ap.add_argument("--experts", type=int, default=0)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--microbatches", type=int, default=2)
    args = ap.parse_args()

    bps.init()
    mesh = bps.make_mesh(dp=args.dp, tp=args.tp, sp=args.sp, pp=args.pp,
                         ep=args.ep)
    cfg = hybrid.HybridConfig(
        vocab_size=1024, num_layers=args.layers, d_model=args.d_model,
        num_heads=8, d_ff=4 * args.d_model, max_seq_len=128,
        num_experts=args.experts)
    opt = optax.adamw(1e-3)
    step, init_fn = hybrid.build_hybrid_train_step(
        cfg, opt, mesh, num_microbatches=args.microbatches)
    params = init_fn(jax.random.key(0))
    opt_state = opt.init(params)

    B = 4 * max(args.dp * args.ep, 1) * args.microbatches
    S = 32 * args.sp
    toks = jax.random.randint(jax.random.key(1), (B, S), 0, cfg.vocab_size)
    tgts = jnp.roll(toks, -1, axis=1)
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, (toks, tgts))
        print(f"step {i}: loss={float(loss):.4f}")
    bps.shutdown()


if __name__ == "__main__":
    main()
