"""Cross-barrier pipelining benchmark.

Counterpart of the reference's cross-barrier benchmark
(reference: example/pytorch/benchmark_cross_barrier_byteps.py): compare a
host-synchronous loop (fetch the loss every step) against the
cross-barrier driver that keeps the device queue full.

  python example/jax/benchmark_cross_barrier_byteps.py
"""

import time

import jax
import jax.numpy as jnp
import optax

import byteps_tpu as bps
from byteps_tpu import models


def main():
    bps.init()
    mesh = bps.get_mesh()
    params = models.init_mlp(jax.random.key(0), (256, 512, 512, 10))
    opt = bps.DistributedOptimizer(optax.sgd(0.01))
    step = bps.build_train_step(models.mlp_loss, opt, mesh, donate=False)
    opt_state = opt.init(params)
    x = jax.random.normal(jax.random.key(1), (1024, 256))
    y = (x.sum(-1) > 0).astype(jnp.int32)
    n = 50

    # warmup/compile
    p, s, l = step(params, opt_state, (x, y))
    float(l)

    t0 = time.perf_counter()
    p, s = params, opt_state
    for _ in range(n):
        p, s, loss = step(p, s, (x, y))
        float(loss)                       # host barrier every step
    sync_t = time.perf_counter() - t0

    t0 = time.perf_counter()
    drv = bps.CrossBarrierDriver(step, params, opt_state, max_in_flight=8)
    for _ in range(n):
        drv.submit((x, y))
    drv.finish()
    cb_t = time.perf_counter() - t0

    print(f"synchronous: {n / sync_t:.1f} steps/s")
    print(f"cross-barrier: {n / cb_t:.1f} steps/s "
          f"({sync_t / cb_t:.2f}x)")
    bps.shutdown()


if __name__ == "__main__":
    main()
