"""ResNet-50 ImageNet-style training with byteps_tpu.

Counterpart of the reference's DDP ImageNet example
(reference: example/pytorch/train_imagenet_resnet50_byteps.py).  Synthetic
data keeps it hermetic; wire in a real input pipeline (e.g. grain/tfds)
for actual ImageNet.

  python example/jax/train_imagenet_resnet_byteps.py --model resnet50
"""

import argparse

import jax
import jax.numpy as jnp
import optax

import byteps_tpu as bps
from byteps_tpu import models


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50")
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--base-lr", type=float, default=0.0125)
    ap.add_argument("--warmup-steps", type=int, default=100)
    args = ap.parse_args()

    bps.init()
    mesh = bps.get_mesh()

    model = models.create_cnn(args.model, num_classes=1000)
    x = jnp.ones((args.batch_size, args.image_size, args.image_size, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    variables = bps.broadcast_parameters(variables)

    # linear-scaled LR with warmup (reference scales lr by world size and
    # warms up 5 epochs)
    schedule = bps.callbacks.warmup_schedule(
        bps.callbacks.scaled_lr(args.base_lr), args.warmup_steps,
        optax.cosine_decay_schedule(
            bps.callbacks.scaled_lr(args.base_lr), 10_000))
    opt = bps.DistributedOptimizer(
        optax.sgd(schedule, momentum=0.9, nesterov=True),
        compression=bps.Compression.fp16)
    opt_state = opt.init(variables)
    step = bps.build_train_step(models.cnn_loss_fn(model), opt, mesh)

    labels = jnp.zeros((args.batch_size,), jnp.int32)
    for i in range(args.steps):
        variables, opt_state, loss = step(variables, opt_state, (x, labels))
        bps.mark_step()
        if i % 2 == 0:
            print(f"step {i}: loss={float(loss):.4f}")
    bps.shutdown()


if __name__ == "__main__":
    main()
