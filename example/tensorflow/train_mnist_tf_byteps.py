"""Train an MNIST-style classifier with the TensorFlow/Keras plugin.

TF counterpart of example/jax/train_mnist_byteps.py, mirroring the
reference's example/tensorflow/tensorflow2_mnist.py +
example/keras/keras_mnist.py shape: broadcast initial variables, wrap the
optimizer so gradients are push_pull-averaged across workers, train.

Uses a synthetic MNIST-like dataset so the example runs hermetically.

Run:
    python example/tensorflow/train_mnist_tf_byteps.py --epochs 1
"""

import argparse

import numpy as np

import byteps_tpu.tensorflow as bps


def synthetic_mnist(n=4096, seed=0):
    protos = np.random.RandomState(0).randn(10, 784).astype(np.float32)
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, size=n)
    x = protos[y] + 0.5 * rng.randn(n, 784).astype(np.float32)
    return x, y.astype(np.int64)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--tape", action="store_true",
                    help="use DistributedGradientTape instead of Keras fit")
    args = ap.parse_args()

    bps.init()
    import tensorflow as tf
    import keras
    from byteps_tpu.tensorflow import keras as bps_keras

    keras.utils.set_random_seed(42 + bps.rank())
    x, y = synthetic_mnist(seed=bps.rank())

    model = keras.Sequential([
        keras.layers.Input((784,)),
        keras.layers.Dense(128, activation="relu"),
        keras.layers.Dense(10),
    ])

    if args.tape:
        # Explicit-loop flavor (reference: tensorflow2_mnist.py).
        opt = keras.optimizers.SGD(args.lr)
        loss_fn = keras.losses.SparseCategoricalCrossentropy(
            from_logits=True)
        bps.broadcast_variables(model.variables, root_rank=0)
        bs = args.batch_size
        for epoch in range(args.epochs):
            for i in range(0, len(x), bs):
                xb = tf.convert_to_tensor(x[i:i + bs])
                yb = tf.convert_to_tensor(y[i:i + bs])
                with bps.DistributedGradientTape(tf.GradientTape()) as tape:
                    loss = loss_fn(yb, model(xb, training=True))
                grads = tape.gradient(loss, model.trainable_variables)
                opt.apply_gradients(zip(grads, model.trainable_variables))
            print(f"rank {bps.rank()}/{bps.size()} epoch {epoch}: "
                  f"loss={float(loss):.4f}")
    else:
        opt = bps_keras.DistributedOptimizer(keras.optimizers.SGD(args.lr))
        model.compile(optimizer=opt,
                      loss=keras.losses.SparseCategoricalCrossentropy(
                          from_logits=True),
                      metrics=["accuracy"])
        hist = model.fit(
            x, y, batch_size=args.batch_size, epochs=args.epochs, verbose=0,
            callbacks=[bps_keras.BroadcastGlobalVariablesCallback(0),
                       bps_keras.MetricAverageCallback()])
        acc = hist.history["accuracy"][-1]
        print(f"rank {bps.rank()}/{bps.size()}: "
              f"loss={hist.history['loss'][-1]:.4f} acc={acc:.3f}")
    bps.shutdown()


if __name__ == "__main__":
    main()
