"""Keras training under the byteps MirroredStrategy analog.

The reference routes TF's own distribution API into push_pull via a forked
MirroredStrategy (reference: byteps/tensorflow/distribute/).  Here the
strategy-shaped wrapper broadcasts variables created in scope() and reduces
gradients through the framework wire with chunked packing.

Run (synthetic MNIST-shaped data, works on CPU):
    python example/tensorflow/train_mnist_mirrored_byteps.py --epochs 2
"""

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--num-packs", type=int, default=2)
    args = ap.parse_args()

    import keras
    import byteps_tpu.tensorflow as bps_tf
    from byteps_tpu.tensorflow.distribute import MirroredStrategy

    bps_tf.init()
    strategy = MirroredStrategy(num_packs=args.num_packs)
    print(f"replicas={strategy.num_replicas_in_sync} rank={bps_tf.rank()}")

    rng = np.random.RandomState(0)  # same data every worker; shard via
    x = rng.rand(2048, 28, 28).astype(np.float32)       # distribute_dataset
    y = rng.randint(0, 10, 2048).astype(np.int32)

    with strategy.scope():
        model = keras.Sequential([
            keras.layers.Input((28, 28)),
            keras.layers.Flatten(),
            keras.layers.Dense(128, activation="relu"),
            keras.layers.Dense(10),
        ])
        model.compile(
            optimizer=strategy.distribute_optimizer(
                keras.optimizers.SGD(0.05)),
            loss=keras.losses.SparseCategoricalCrossentropy(
                from_logits=True),
            metrics=["accuracy"])
    print(f"broadcast {strategy.broadcast_count} variables from root")

    hist = model.fit(x, y, epochs=args.epochs,
                     batch_size=args.batch_size, verbose=0)
    losses = hist.history["loss"]
    print(f"loss {losses[0]:.4f} -> {losses[-1]:.4f}")
    if len(losses) > 1:
        assert losses[-1] < losses[0]
    print("mirrored strategy training done")
    bps_tf.shutdown()


if __name__ == "__main__":
    main()
