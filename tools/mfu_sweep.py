"""On-chip MFU sweep driver for the flagship bench.

Runs a list of bench configurations serially, each in its own disposable
subprocess (the chip's per-process lock is released between runs), records
every JSON line to a results file, and PROBES TUNNEL HEALTH between runs —
a crashed remote compile can wedge the device tunnel for every subsequent
process (round-4 postmortem: two OOM-ing remat-policy compiles took the
tunnel down for hours), so the sweep stops early rather than queueing more
compiles into a wedged service.

Usage:  python tools/mfu_sweep.py [results.jsonl]

Config list lives in SWEEP below — edit freely; each entry is a dict of
extra env vars layered on the flagship bench defaults.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Conventions (learned over passes 1-4, results in bench_runs/):
# - an anchor of the current default opens a pass whenever the default
#   moved, so every sweep file self-calibrates against the same hour;
# - every entry pins BENCH_BATCH explicitly so a future default change
#   can't silently move an entry into a different memory regime;
# - entries that escalate memory carry `group`: once one entry of a
#   group fails (OOM), later entries of the SAME group are skipped — an
#   OOM-ing remote compile is exactly what wedged the tunnel in the
#   pass-2 postmortem.
#
# Pass 6.  Pass 5 (bench_runs/r04_sweep5{,b}.jsonl) established the
# long-S block ladder (blk512 27.0k > 256 20.7k > 128 15.4k tok/s at
# llama_300m seq 2048 batch 8; dense 15.9k) before the tunnel wedged
# again.  This pass: (a) flagship anchor re-run under the new auto
# rule, (b) the BENCH_UNROLL ladder (scan_unroll groups layers per
# scan iteration — scheduling freedom vs code size, unmeasured),
# (c) the llama batch escalation pass 5 never reached (now under the
# winning blk512), (d) the asymmetric-tile question, (e) the dense
# batch-64 anchor from the pass-3 list.
SWEEP = [
    {"name": "flagship_anchor",
     "env": {"BENCH_BATCH": "64", "BENCH_COST": "1"}},
    {"name": "flagship_unroll2", "group": "unroll",
     "env": {"BENCH_BATCH": "64", "BENCH_UNROLL": "2"}},
    {"name": "flagship_unroll4", "group": "unroll",
     "env": {"BENCH_BATCH": "64", "BENCH_UNROLL": "4"}},
    # proj selective remat at the tuned batch: at 48 it matched full remat
    # within noise, but it skips ~2/3 of the recomputed matmul FLOPs — if
    # it still fits at 64 (flash keeps the S^2 logits out of HBM), the
    # saved recompute should finally show.  Grouped: OOM stops the pair.
    {"name": "flagship_proj_b64", "group": "proj",
     "env": {"BENCH_BATCH": "64", "BENCH_REMAT_POLICY": "proj"}},
    {"name": "flagship_proj_b64_unroll2", "group": "proj",
     "env": {"BENCH_BATCH": "64", "BENCH_REMAT_POLICY": "proj",
             "BENCH_UNROLL": "2"}},
    {"name": "l300m_b16_blk512", "group": "lbatch",
     "env": {"BENCH_MODEL": "llama_300m", "BENCH_ATTN": "flash",
             "BENCH_BATCH": "16", "BENCH_ATTN_BLOCK": "512"}},
    {"name": "l300m_b24_blk512", "group": "lbatch",
     "env": {"BENCH_MODEL": "llama_300m", "BENCH_ATTN": "flash",
             "BENCH_BATCH": "24", "BENCH_ATTN_BLOCK": "512"}},
    {"name": "dense_b64",
     "env": {"BENCH_ATTN": "dense", "BENCH_BATCH": "64"}},
    # Asymmetric tiles (BENCH_ATTN_BLOCK_K decouples the K/V tile from
    # the Q tile): at causal long-S a wide Q tile keeps programs fat
    # while a narrow K tile trims masked diagonal waste — unmeasured.
    {"name": "l300m_q512_k256", "group": "llama",
     "env": {"BENCH_MODEL": "llama_300m", "BENCH_ATTN": "flash",
             "BENCH_BATCH": "8", "BENCH_ATTN_BLOCK": "512",
             "BENCH_ATTN_BLOCK_K": "256"}},
    {"name": "l300m_s2048_unroll2",
     "env": {"BENCH_MODEL": "llama_300m", "BENCH_ATTN": "flash",
             "BENCH_BATCH": "8", "BENCH_ATTN_BLOCK": "512",
             "BENCH_UNROLL": "2"}},
    # Gathered-sequence A/B: the strict ring/Ulysses path runs flash at
    # S >= 8k, where the new 512 auto tile is an extrapolation from the
    # S=2048 ladder — settle it on-chip (grouped: the 8k compile is the
    # memory-heavy one; an OOM skips the second leg).
    {"name": "l300m_s8192_blk512", "group": "s8k",
     "env": {"BENCH_MODEL": "llama_300m", "BENCH_SEQ": "8192",
             "BENCH_ATTN": "flash", "BENCH_BATCH": "1",
             "BENCH_ATTN_BLOCK": "512"}},
    {"name": "l300m_s8192_blk128", "group": "s8k",
     "env": {"BENCH_MODEL": "llama_300m", "BENCH_SEQ": "8192",
             "BENCH_ATTN": "flash", "BENCH_BATCH": "1",
             "BENCH_ATTN_BLOCK": "128"}},
]

PROBE = ("import jax, jax.numpy as jnp; "
         "print(float((jnp.ones((256,256))@jnp.ones((256,256))).sum()))")


def tunnel_alive(timeout: float = 120.0) -> bool:
    try:
        r = subprocess.run([sys.executable, "-c", PROBE], timeout=timeout,
                           capture_output=True, text=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def run_one(entry: dict, timeout: float) -> dict:
    env = dict(os.environ)
    env.update(entry["env"])
    env["BENCH_EXEC_CHILD"] = "1"   # single measurement, no recovery ladder
    t0 = time.time()
    try:
        r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                           env=env, timeout=timeout, capture_output=True,
                           text=True)
        rc, out, err = r.returncode, r.stdout, r.stderr
    except subprocess.TimeoutExpired as e:
        rc = 124
        out = (e.stdout or b"").decode(errors="replace") \
            if isinstance(e.stdout, bytes) else (e.stdout or "")
        err = (e.stderr or b"").decode(errors="replace") \
            if isinstance(e.stderr, bytes) else (e.stderr or "")
    rec = {"name": entry["name"], "env": entry["env"], "rc": rc,
           "wall_s": round(time.time() - t0, 1)}
    lines = [ln for ln in out.strip().splitlines() if ln.startswith("{")]
    try:
        if rc == 0 and lines:
            rec["result"] = json.loads(lines[-1])
        else:
            rec["stderr_tail"] = err[-1500:]
    except json.JSONDecodeError:
        # A half-flushed line from a dying child must not abort the sweep.
        rec["bad_stdout_tail"] = out[-500:]
        rec["stderr_tail"] = err[-1000:]
    return rec


def main() -> None:
    out_path = sys.argv[1] if len(sys.argv) > 1 else \
        os.path.join(REPO, "sweep_results.jsonl")
    timeout = float(os.environ.get("SWEEP_RUN_TIMEOUT", "700"))
    failed_groups = set()
    with open(out_path, "a") as f:
        for entry in SWEEP:
            if entry.get("group") in failed_groups:
                print(f"[sweep] skipping {entry['name']} (group "
                      f"{entry['group']!r} already failed)", file=sys.stderr)
                f.write(json.dumps({"name": entry["name"],
                                    "skipped": "group failed"}) + "\n")
                f.flush()
                continue
            if not tunnel_alive():
                print(f"[sweep] tunnel wedged before {entry['name']}; "
                      f"stopping", file=sys.stderr)
                f.write(json.dumps({"name": entry["name"],
                                    "skipped": "tunnel wedged"}) + "\n")
                f.flush()
                break
            print(f"[sweep] running {entry['name']} ...", file=sys.stderr)
            rec = run_one(entry, timeout)
            f.write(json.dumps(rec) + "\n")
            f.flush()
            if rec["rc"] != 0 and entry.get("group"):
                failed_groups.add(entry["group"])
            res = rec.get("result", {}).get("detail", {})
            print(f"[sweep] {entry['name']}: rc={rec['rc']} "
                  f"tok/s={res.get('tokens_per_sec_per_chip')} "
                  f"mfu={res.get('mfu')}", file=sys.stderr)
    print(f"[sweep] results in {out_path}", file=sys.stderr)


if __name__ == "__main__":
    main()
