"""On-chip MFU sweep driver for the flagship bench.

Runs a list of bench configurations serially, each in its own disposable
subprocess (the chip's per-process lock is released between runs), records
every JSON line to a results file, and PROBES TUNNEL HEALTH between runs —
a crashed remote compile can wedge the device tunnel for every subsequent
process (round-4 postmortem: two OOM-ing remat-policy compiles took the
tunnel down for hours), so the sweep stops early rather than queueing more
compiles into a wedged service.

Usage:  python tools/mfu_sweep.py [results.jsonl]

Config list lives in SWEEP below — edit freely; each entry is a dict of
extra env vars layered on the flagship bench defaults.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Conventions (learned over passes 1-4, results in bench_runs/):
# - an anchor of the current default opens a pass whenever the default
#   moved, so every sweep file self-calibrates against the same hour;
# - every entry pins BENCH_BATCH explicitly so a future default change
#   can't silently move an entry into a different memory regime;
# - entries that escalate memory carry `group`: once one entry of a
#   group fails (OOM), later entries of the SAME group are skipped — an
#   OOM-ing remote compile is exactly what wedged the tunnel in the
#   pass-2 postmortem.
#
# Pass 7 (round 5).  Priorities from the round-4 review, ordered so the
# never-measured evidence lands FIRST if the tunnel wedges mid-pass:
# (a) flagship anchor (self-calibration), (b) the CNN baseline rows that
# have existed for four rounds with zero on-chip data, (c) the levers
# round 4 built but never measured (proj remat at b64/96, the no-remat
# ladder, asymmetric K tile at S=512, CE chunk ladder, unroll), (d) the
# truncated long-context sweeps (llama batch escalation, llama_1b
# S=2048, S=8192 end-to-end).
SWEEP = [
    {"name": "flagship_anchor",
     "env": {"BENCH_BATCH": "64", "BENCH_COST": "1"}},
    # CNN rows: BS=64/chip like the reference's headline table
    # (reference docs/performance.md:5-12).  fp32, 224x224.
    {"name": "cnn_resnet50", "timeout": 1200,
     "env": {"BENCH_CNN": "resnet50", "BENCH_CNN_BATCH": "64"}},
    {"name": "cnn_vgg16", "timeout": 1200, "group": "cnn_vgg",
     "env": {"BENCH_CNN": "vgg16", "BENCH_CNN_BATCH": "64"}},
    # proj selective remat at the tuned batch: skips ~2/3 of the
    # recomputed matmul FLOPs vs full remat.  (The b96/no-remat
    # escalations live at the END of the list: an OOM-ing remote
    # compile is the known tunnel-wedge trigger — pass-2 postmortem —
    # and must not be able to take the rest of the pass down with it.)
    {"name": "flagship_proj_b64", "group": "proj",
     "env": {"BENCH_BATCH": "64", "BENCH_REMAT_POLICY": "proj"}},
    # No remat at all: zero recompute, activations live in HBM.  b16 is
    # the safe rung (flash keeps the S^2 logits out of HBM); the b24/32
    # escalation is at the tail with the other OOM risks.
    {"name": "flagship_noremat_b16", "group": "noremat",
     "env": {"BENCH_BATCH": "16", "BENCH_REMAT": "0"}},
    # Asymmetric tiles at the flagship geometry: narrow K tile trims
    # masked diagonal waste in the causal kernel.
    {"name": "flagship_q512_k256",
     "env": {"BENCH_BATCH": "64", "BENCH_ATTN_BLOCK_K": "256"}},
    # CE chunk ladder: 2048 is the tuned default; the sweep has never
    # measured either neighbor at batch 64.
    {"name": "flagship_ce4096",
     "env": {"BENCH_BATCH": "64", "BENCH_CE_CHUNK": "4096"}},
    {"name": "flagship_ce8192",
     "env": {"BENCH_BATCH": "64", "BENCH_CE_CHUNK": "8192"}},
    {"name": "flagship_unroll2",
     "env": {"BENCH_BATCH": "64", "BENCH_UNROLL": "2"}},
    # Long context: the batch escalation pass 5 never reached (under the
    # winning blk512), then llama_1b at S=2048 (never ran: sweep4 died).
    {"name": "l300m_b16_blk512", "group": "lbatch",
     "env": {"BENCH_MODEL": "llama_300m", "BENCH_ATTN": "flash",
             "BENCH_BATCH": "16", "BENCH_ATTN_BLOCK": "512"}},
    {"name": "l300m_b24_blk512", "group": "lbatch",
     "env": {"BENCH_MODEL": "llama_300m", "BENCH_ATTN": "flash",
             "BENCH_BATCH": "24", "BENCH_ATTN_BLOCK": "512"}},
    {"name": "l1b_s2048_blk512", "group": "l1b", "timeout": 1200,
     "env": {"BENCH_MODEL": "llama_1b", "BENCH_ATTN": "flash",
             "BENCH_BATCH": "4", "BENCH_ATTN_BLOCK": "512"}},
    {"name": "l1b_s2048_blk256", "group": "l1b", "timeout": 1200,
     "env": {"BENCH_MODEL": "llama_1b", "BENCH_ATTN": "flash",
             "BENCH_BATCH": "4", "BENCH_ATTN_BLOCK": "256"}},
    # Long-S selective remat: the O(S^2)-free proj policy is the round-4
    # lever for pushing S=2048 MFU past 0.30.
    {"name": "l300m_s2048_proj", "group": "lproj",
     "env": {"BENCH_MODEL": "llama_300m", "BENCH_ATTN": "flash",
             "BENCH_BATCH": "8", "BENCH_ATTN_BLOCK": "512",
             "BENCH_REMAT_POLICY": "proj"}},
    {"name": "l300m_s2048_noremat", "group": "lproj",
     "env": {"BENCH_MODEL": "llama_300m", "BENCH_ATTN": "flash",
             "BENCH_BATCH": "8", "BENCH_ATTN_BLOCK": "512",
             "BENCH_REMAT": "0"}},
    # S=8192 end-to-end (the kernel microbench says streaming flash is
    # 1.61x at S=4096 — prove it on a full train step).  Grouped: the 8k
    # compile is the memory-heavy one; an OOM skips the second leg.
    {"name": "l300m_s8192_blk512", "group": "s8k", "timeout": 1200,
     "env": {"BENCH_MODEL": "llama_300m", "BENCH_SEQ": "8192",
             "BENCH_ATTN": "flash", "BENCH_BATCH": "1",
             "BENCH_ATTN_BLOCK": "512"}},
    {"name": "l300m_s8192_blk128", "group": "s8k", "timeout": 1200,
     "env": {"BENCH_MODEL": "llama_300m", "BENCH_SEQ": "8192",
             "BENCH_ATTN": "flash", "BENCH_BATCH": "1",
             "BENCH_ATTN_BLOCK": "128"}},
    # ---- memory-escalation tail: every entry below is an OOM
    # candidate, and an OOM-ing remote compile can wedge the tunnel for
    # everything after it — so nothing of value runs after these.
    {"name": "flagship_noremat_b24", "group": "noremat",
     "env": {"BENCH_BATCH": "24", "BENCH_REMAT": "0"}},
    {"name": "flagship_noremat_b32", "group": "noremat",
     "env": {"BENCH_BATCH": "32", "BENCH_REMAT": "0"}},
    {"name": "flagship_proj_b96", "group": "proj",
     "env": {"BENCH_BATCH": "96", "BENCH_REMAT_POLICY": "proj"}},
]

# The tunnel-health probe moved to byteps_tpu.common.devprof (PR 20):
# the live device sentinel corroborates a wedge conviction with the
# SAME subprocess probe this sweep runs between entries, so the two
# verdicts cannot drift.  Re-exported here under the original names.
sys.path.insert(0, REPO)
from byteps_tpu.common.devprof import PROBE, tunnel_alive  # noqa: E402,F401


def run_one(entry: dict, timeout: float) -> dict:
    env = dict(os.environ)
    env.update(entry["env"])
    env["BENCH_EXEC_CHILD"] = "1"   # single measurement, no recovery ladder
    t0 = time.time()
    try:
        r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                           env=env, timeout=timeout, capture_output=True,
                           text=True)
        rc, out, err = r.returncode, r.stdout, r.stderr
    except subprocess.TimeoutExpired as e:
        rc = 124
        out = (e.stdout or b"").decode(errors="replace") \
            if isinstance(e.stdout, bytes) else (e.stdout or "")
        err = (e.stderr or b"").decode(errors="replace") \
            if isinstance(e.stderr, bytes) else (e.stderr or "")
    rec = {"name": entry["name"], "env": entry["env"], "rc": rc,
           "ts": time.strftime("%Y-%m-%d %H:%M"),
           "wall_s": round(time.time() - t0, 1)}
    lines = [ln for ln in out.strip().splitlines() if ln.startswith("{")]
    try:
        if rc == 0 and lines:
            rec["result"] = json.loads(lines[-1])
        else:
            rec["stderr_tail"] = err[-1500:]
    except json.JSONDecodeError:
        # A half-flushed line from a dying child must not abort the sweep.
        rec["bad_stdout_tail"] = out[-500:]
        rec["stderr_tail"] = err[-1000:]
    return rec


def main() -> None:
    out_path = sys.argv[1] if len(sys.argv) > 1 else \
        os.path.join(REPO, "sweep_results.jsonl")
    timeout = float(os.environ.get("SWEEP_RUN_TIMEOUT", "700"))
    failed_groups = set()
    with open(out_path, "a") as f:
        for entry in SWEEP:
            if entry.get("group") in failed_groups:
                print(f"[sweep] skipping {entry['name']} (group "
                      f"{entry['group']!r} already failed)", file=sys.stderr)
                f.write(json.dumps({"name": entry["name"],
                                    "skipped": "group failed"}) + "\n")
                f.flush()
                continue
            if not tunnel_alive():
                print(f"[sweep] tunnel wedged before {entry['name']}; "
                      f"stopping", file=sys.stderr)
                f.write(json.dumps({"name": entry["name"],
                                    "skipped": "tunnel wedged"}) + "\n")
                f.flush()
                break
            print(f"[sweep] running {entry['name']} ...", file=sys.stderr)
            rec = run_one(entry, float(entry.get("timeout", timeout)))
            f.write(json.dumps(rec) + "\n")
            f.flush()
            if rec["rc"] != 0 and entry.get("group"):
                failed_groups.add(entry["group"])
            res = rec.get("result", {}).get("detail", {})
            print(f"[sweep] {entry['name']}: rc={rec['rc']} "
                  f"tok/s={res.get('tokens_per_sec_per_chip')} "
                  f"mfu={res.get('mfu')}", file=sys.stderr)
    print(f"[sweep] results in {out_path}", file=sys.stderr)


if __name__ == "__main__":
    main()
