#!/usr/bin/env python
"""bps_doctor — run the continuous-diagnosis rules against a live job
or a dead one's recordings.

The SAME declarative rule set (`byteps_tpu/common/doctor.py`) that runs
inside every worker with ``BYTEPS_TPU_SIGNAL_WINDOW_S`` > 0 runs here,
in two modes:

**Live** — poll a running worker's metrics endpoint (the ``/signals``
JSON route serves the signal plane's window history) and evaluate each
new window as it closes::

    python tools/bps_doctor.py --url http://worker:9100   # follow
    python tools/bps_doctor.py --port 9100 --once         # one verdict

**Offline** — replay recordings from a dead run::

    python tools/bps_doctor.py /shared/postmortems        # bundle dir
    python tools/bps_doctor.py bundle.json --json         # one bundle
    python tools/bps_doctor.py metrics.jsonl              # metrics log

A postmortem bundle (``BYTEPS_TPU_POSTMORTEM_DIR``) carries the signal
plane's recent window history in its ``diagnosis``/``signals`` extra
sections — offline replay over a bundle therefore sees exactly what the
live doctor saw.  A metrics JSONL (``BYTEPS_TPU_METRICS_LOG``) yields
windows with the metrics series only (no per-key records or flight
events); rules that need those stay quiet, identically live or offline.

``--json`` emits one machine-readable object.  Exit codes: 0 = ran
(healthy or not; read the output), 1 = no input/endpoint.  Add
``--fail-on-findings`` to exit 3 when any finding FIRED during the
evaluated stream — open at the end or not: for a CI gate over a dead
run's recordings, a barrier stall that later "cleared" still deserves a
red build.  No dependencies beyond the stdlib + the byteps_tpu package.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
import urllib.request

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from byteps_tpu.common import doctor  # noqa: E402

BUNDLE_SCHEMA = "bps-postmortem-v1"


# ---------------------------------------------------------------------------
# Offline input loading
# ---------------------------------------------------------------------------
def _load_json(path: str):
    with open(path) as f:
        return json.load(f)


def load_offline(paths) -> list:
    """[(source_label, [window summaries])] from bundle files, bundle
    directories, and metrics JSONLs.  Each source is evaluated on its
    own (a bundle is one worker's view; merging histories would
    double-count counters)."""
    sources = []
    files = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(
                os.path.join(p, "bps-postmortem-*.json"))))
        else:
            files.append(p)
    for f in files:
        try:
            first = open(f).read(4096).lstrip()
        except OSError as e:
            print(f"bps_doctor: skipping {f}: {e}", file=sys.stderr)
            continue
        try:
            if first.startswith("{") and '"bps-postmortem-v1"' in first:
                doc = _load_json(f)
                if doc.get("schema") != BUNDLE_SCHEMA:
                    raise ValueError("not a postmortem bundle")
                extra = doc.get("extra") or {}
                windows = extra.get("signals") or []
                label = f"r{doc.get('rank', '?')}:{os.path.basename(f)}"
                if not windows:
                    # A bundle from a run with the plane off still has
                    # its final metrics snapshot: evaluate what one
                    # window's worth of gauges can say (deltas are 0).
                    windows = [{"schema": "bps-signal-window-v1",
                                "window": 0,
                                "ts": (doc.get("clock") or {}).get(
                                    "wall", 0.0),
                                "dur_s": 0.0, "keys": {},
                                "metrics": {
                                    k: v for k, v in (doc.get("metrics")
                                                      or {}).items()
                                    if isinstance(v, (int, float))},
                                "events": {}}]
                recorded = (extra.get("diagnosis") or {})
                sources.append((label, windows, recorded))
            else:
                # Metrics JSONL: one {"ts", "metrics"} object per line.
                lines = []
                with open(f) as fh:
                    for raw in fh:
                        raw = raw.strip()
                        if raw:
                            lines.append(json.loads(raw))
                sources.append((os.path.basename(f),
                                doctor.summaries_from_metrics_jsonl(lines),
                                {}))
        except (OSError, ValueError, KeyError) as e:
            print(f"bps_doctor: skipping {f}: {e}", file=sys.stderr)
    return sources


def run_offline(paths, as_json: bool) -> tuple:
    """Returns (exit_code, any_findings)."""
    sources = load_offline(paths)
    if not sources:
        print("bps_doctor: no usable input (want postmortem bundles, a "
              "bundle directory, or a metrics JSONL)", file=sys.stderr)
        return 1, False
    results = []
    any_findings = False
    for label, windows, recorded in sources:
        diag = doctor.evaluate_stream(windows)
        results.append({"source": label, "diagnosis": diag,
                        "recorded_open": recorded.get("open", [])})
        if diag["open"] or diag["history"]:
            any_findings = True
    if as_json:
        print(json.dumps({"mode": "offline", "sources": results}))
        return 0, any_findings
    for r in results:
        d = r["diagnosis"]
        print(f"== {r['source']}  ({d['windows_evaluated']} window(s) "
              f"replayed)")
        _print_diag(d)
        rec = r["recorded_open"]
        if rec:
            print(f"  recorded at dump time ({len(rec)} open):")
            for f in rec:
                print(f"    [{f.get('severity', '?')}] "
                      f"{f.get('rule', '?')} ({f.get('subject', '')})")
        print()
    return 0, any_findings


def _print_diag(d: dict) -> None:
    if d.get("healthy"):
        print(f"  healthy — no open findings "
              f"({d.get('findings_total', 0)} opened over the run)")
    for f in d.get("open", []):
        print(f"  [{f['severity'].upper():<8}] {f['rule']} "
              f"({f['subject']})")
        print(f"      {f['summary']}")
        print(f"      playbook: {f['playbook']}")
    open_keys = {(g["rule"], g["subject"]) for g in d.get("open", [])}
    closed = [f for f in d.get("history", [])
              if (f["rule"], f["subject"]) not in open_keys]
    if closed:
        print(f"  cleared during the run: " + ", ".join(
            sorted({f"{f['rule']}({f['subject']})" for f in closed})))


# ---------------------------------------------------------------------------
# Fleet mode (--fleet): the cross-worker rule set over the MERGED view
# ---------------------------------------------------------------------------
def _load_bundles(paths) -> list:
    files = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(
                os.path.join(p, "bps-postmortem-*.json"))))
        else:
            files.append(p)
    bundles = []
    for f in files:
        try:
            doc = _load_json(f)
            if doc.get("schema") == BUNDLE_SCHEMA:
                bundles.append(doc)
        except (OSError, ValueError) as e:
            print(f"bps_doctor: skipping {f}: {e}", file=sys.stderr)
    return bundles


def run_fleet_offline(paths, as_json: bool) -> tuple:
    """Merge every bundle's ``fleet.published`` ring (each worker's
    exact CMD_WINDOW docs) back into the view CMD_FLEET would have
    served, align, and evaluate the fleet rules — identical to the live
    verdict by construction."""
    bundles = _load_bundles(paths)
    view = doctor.fleet_view_from_bundles(bundles)
    fw = doctor.fleet_windows_from_view(view)
    if not fw:
        print("bps_doctor: no fleet windows in the given bundle(s) — "
              "was BYTEPS_TPU_FLEET=1 set on the workers?",
              file=sys.stderr)
        return 1, False
    diag = doctor.evaluate_fleet_stream(fw)
    any_findings = bool(diag["open"] or diag["history"])
    if as_json:
        print(json.dumps({"mode": "fleet-offline",
                          "workers": sorted(view.get("workers") or ()),
                          "diagnosis": diag}))
    else:
        print(f"== fleet ({len(view.get('workers') or ())} worker "
              f"ring(s), {diag['windows_evaluated']} aligned window(s) "
              f"replayed)")
        _print_diag(diag)
    return 0, any_findings


def run_fleet_live(base: str, interval: float, once: bool,
                   as_json: bool) -> tuple:
    """Poll ONE worker's ``/fleet`` route (worker 0's endpoint — the
    one that fetches the merged CMD_FLEET view) and evaluate the fleet
    rules locally over the raw view, exactly as the in-job engine and
    the offline replay do."""
    eng = doctor.DoctorEngine(rules=doctor.FLEET_RULES, emit=False)
    seen = -1
    printed = set()
    while True:
        try:
            doc = _fetch_json(base + "/fleet")
        except OSError as e:
            print(f"bps_doctor: cannot reach {base}/fleet: {e} — is "
                  f"BYTEPS_TPU_FLEET=1 set and this worker 0's "
                  f"endpoint?", file=sys.stderr)
            if once:
                return 1, False
            time.sleep(interval)
            continue
        if not doc.get("armed"):
            print(f"bps_doctor: {base} reports the fleet plane unarmed "
                  f"(BYTEPS_TPU_FLEET=1 missing, or the bootstrap "
                  f"probe downgraded against an old server tier)",
                  file=sys.stderr)
            return 1, False
        fw = doctor.fleet_windows_from_view(doc.get("view") or {})
        if not fw:
            # Worker-N endpoint (publishes, never fetches) or no window
            # has rolled yet: nothing mergeable here.
            fw = doc.get("windows") or []
        top = max((int(w.get("window", -1)) for w in fw), default=-1)
        if top < seen:
            print(f"bps_doctor: window index reset ({top} < {seen}) — "
                  f"worker restarted, re-evaluating from scratch",
                  file=sys.stderr)
            eng = doctor.DoctorEngine(rules=doctor.FLEET_RULES,
                                      emit=False)
            seen = -1
        for w in fw:
            if int(w.get("window", -1)) > seen:
                seen = int(w.get("window", -1))
                fired = eng.observe(w)
                if not (once or as_json):
                    for f in fired:
                        key = (f["rule"], f["subject"],
                               f["first_window"])
                        if key not in printed:
                            printed.add(key)
                            print(f"[window {f['window']}] "
                                  f"[{f['severity'].upper()}] "
                                  f"{f['rule']} ({f['subject']}): "
                                  f"{f['summary']}\n    playbook: "
                                  f"{f['playbook']}")
        diag = eng.diagnosis()
        if once:
            if as_json:
                out = {"mode": "fleet-live", "diagnosis": diag}
                if doc.get("goodput"):
                    out["goodput"] = doc["goodput"]
                print(json.dumps(out))
            else:
                print(f"== {base} fleet ({len(fw)} aligned window(s))")
                _print_diag(diag)
                gp = doc.get("goodput")
                if gp:
                    print(f"  goodput: {gp.get('goodput_pct', 0.0):.1f}% "
                          f"compute over {gp.get('total_s', 0.0):.1f}s "
                          f"fleet wall-time (window "
                          f"{gp.get('window')})")
            return 0, bool(diag["open"] or diag["history"])
        time.sleep(interval)


# ---------------------------------------------------------------------------
# Live mode
# ---------------------------------------------------------------------------
def _fetch_json(url: str, timeout: float = 5.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


def run_live(base: str, interval: float, once: bool,
             as_json: bool) -> tuple:
    """Poll ``<base>/signals`` and evaluate each new window with a local
    engine — the live job's own doctor and this one run the same rules
    over the same summaries, so they agree by construction."""
    eng = doctor.DoctorEngine(emit=False)
    seen = -1
    printed = set()
    while True:
        try:
            doc = _fetch_json(base + "/signals")
        except OSError as e:
            print(f"bps_doctor: cannot reach {base}/signals: {e} — is "
                  f"BYTEPS_TPU_SIGNAL_WINDOW_S > 0 and "
                  f"BYTEPS_TPU_METRICS_PORT set on the worker?",
                  file=sys.stderr)
            if once:
                return 1, False
            time.sleep(interval)
            continue
        windows = doc.get("windows") or []
        top = max((int(w.get("window", -1)) for w in windows),
                  default=-1)
        if top < seen:
            # Window indices went BACKWARDS: the worker restarted (a new
            # plane counts from 0).  Start a fresh engine — the old
            # high-water mark would silently swallow the new run's
            # windows for as long as its history.
            print(f"bps_doctor: window index reset ({top} < {seen}) — "
                  f"worker restarted, re-evaluating from scratch",
                  file=sys.stderr)
            eng = doctor.DoctorEngine(emit=False)
            seen = -1
        for w in windows:
            if int(w.get("window", -1)) > seen:
                seen = int(w.get("window", -1))
                fired = eng.observe(w)
                if not (once or as_json):
                    for f in fired:
                        key = (f["rule"], f["subject"],
                               f["first_window"])
                        if key not in printed:
                            printed.add(key)
                            print(f"[window {f['window']}] "
                                  f"[{f['severity'].upper()}] "
                                  f"{f['rule']} ({f['subject']}): "
                                  f"{f['summary']}\n    playbook: "
                                  f"{f['playbook']}")
        diag = eng.diagnosis()
        if once:
            if as_json:
                print(json.dumps({"mode": "live", "diagnosis": diag}))
            else:
                print(f"== {base}  ({len(windows)} window(s) in "
                      f"history)")
                _print_diag(diag)
            return 0, bool(diag["open"] or diag["history"])
        time.sleep(interval)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="offline inputs: postmortem bundle file(s)/"
                         "dir(s) or metrics JSONL(s)")
    ap.add_argument("--url", help="live mode: worker metrics endpoint "
                                  "base (http://host:port)")
    ap.add_argument("--port", type=int,
                    help="live mode shorthand for http://127.0.0.1:PORT")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="live poll interval seconds (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="live mode: one evaluation pass, then exit")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (implies --once live)")
    ap.add_argument("--fail-on-findings", action="store_true",
                    help="exit 3 when any finding fired during the "
                         "run, even if it later cleared (CI gate)")
    ap.add_argument("--fleet", action="store_true",
                    help="run the FLEET rule set over the merged "
                         "cross-worker view: live against one /fleet "
                         "endpoint (worker 0), offline against the "
                         "merged postmortem bundles")
    args = ap.parse_args(argv)
    if bool(args.paths) == bool(args.url or args.port):
        ap.error("need offline paths OR --url/--port (not both)")
    if args.paths:
        if args.fleet:
            rc, findings = run_fleet_offline(args.paths, args.json)
        else:
            rc, findings = run_offline(args.paths, args.json)
    else:
        base = (args.url or f"http://127.0.0.1:{args.port}").rstrip("/")
        base = base.rsplit("/metrics", 1)[0]
        run = run_fleet_live if args.fleet else run_live
        rc, findings = run(base, args.interval,
                           once=args.once or args.json,
                           as_json=args.json)
    if rc == 0 and args.fail_on_findings and findings:
        return 3
    return rc


if __name__ == "__main__":
    sys.exit(main())
