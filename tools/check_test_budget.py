#!/usr/bin/env python
"""check_test_budget — per-test duration budget for the tier-1 suite.

The tier-1 verify runs the whole non-slow suite under one hard timeout
(870s, ROADMAP).  Nothing has historically capped an INDIVIDUAL test,
so the growing e2e set can blow the global timeout one slow test at a
time, and the failure mode is the worst one — a timeout kill with no
culprit named.  This gate closes that: any non-``slow``-marked test
whose call phase exceeds ``--budget`` seconds (default 60) fails the
check BY NAME.

Data source, in order of preference:

1. ``tests/.last_durations.json`` — written by the conftest recorder at
   every pytest session end: the complete ``pytest --durations`` data
   (call-phase seconds + slow-marker flag per nodeid), machine-readable
   and untruncated.
2. ``--log FILE`` — a pytest output log produced WITH ``--durations=0``;
   the classic ``12.34s call path::test`` rows are parsed instead
   (slow-marker information is absent there, so pass ``--log`` only for
   runs that already deselected slow tests, e.g. the tier-1 command).

Wired as a fast tier-1 test (tests/test_test_budget.py) over the
PREVIOUS run's recording — a budget breach lands on the next run, which
is exactly when a reviewer is still looking at the PR that caused it.
Also runnable standalone:

    python tools/check_test_budget.py [--budget 60] [--json]
    python tools/check_test_budget.py --log /tmp/_t1.log

Exit codes: 0 = within budget (or no data yet), 1 = budget exceeded,
2 = usage error.  ``BYTEPS_TPU_TEST_BUDGET_S`` overrides the default
budget (documented in docs/env.md).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, Optional

DEFAULT_BUDGET_S = 60.0

#: pytest --durations row: "  12.34s call     tests/test_x.py::test_y"
_DURATION_ROW = re.compile(
    r"^\s*(\d+(?:\.\d+)?)s\s+(call|setup|teardown)\s+(\S+)\s*$")


def default_data_path() -> str:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(root, "tests", ".last_durations.json")


def load_recorded(path: str) -> Optional[Dict[str, dict]]:
    """The conftest recorder's {nodeid: {"duration", "slow"}} map, or
    None when no recording exists yet (first run / clean checkout)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError:
        return None
    except ValueError:
        print(f"check_test_budget: unreadable recording {path}; "
              f"treating as no data", file=sys.stderr)
        return None
    d = doc.get("durations")
    return d if isinstance(d, dict) else None


def parse_durations_log(text: str) -> Dict[str, dict]:
    """{nodeid: {"duration", "slow": False}} from a pytest log produced
    with ``--durations=0`` — call-phase rows only (setup/teardown waits
    are fixture costs, budgeted with the test that pays them in the
    recorder path but unattributable here)."""
    out: Dict[str, dict] = {}
    for line in text.splitlines():
        m = _DURATION_ROW.match(line)
        if m and m.group(2) == "call":
            nodeid = m.group(3)
            dur = float(m.group(1))
            if dur > out.get(nodeid, {}).get("duration", -1.0):
                out[nodeid] = {"duration": dur, "slow": False}
    return out


def check(durations: Dict[str, dict],
          budget_s: float = DEFAULT_BUDGET_S) -> dict:
    """The gate as a pure function (the self-test's entry point):
    non-slow tests over budget, slowest first."""
    offenders = []
    slow_exempt = 0
    for nodeid, rec in durations.items():
        dur = float(rec.get("duration", 0.0))
        if rec.get("slow"):
            slow_exempt += 1
            continue
        if dur > budget_s:
            offenders.append({"nodeid": nodeid,
                              "duration": round(dur, 3)})
    offenders.sort(key=lambda r: -r["duration"])
    return {"budget_s": budget_s, "tests": len(durations),
            "slow_exempt": slow_exempt, "offenders": offenders}


def render(report: dict) -> str:
    lines = [f"check_test_budget: {report['tests']} test(s), budget "
             f"{report['budget_s']:g}s per non-slow test "
             f"({report['slow_exempt']} slow-marked exempt)"]
    for o in report["offenders"]:
        lines.append(f"  {o['duration']:8.1f}s  {o['nodeid']}  "
                     f"<-- OVER BUDGET (mark it slow, split it, or "
                     f"speed it up)")
    lines.append(f"{len(report['offenders'])} test(s) over budget"
                 if report["offenders"] else "all tests within budget")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", nargs="?", default=default_data_path(),
                    help="durations recording (default: "
                         "tests/.last_durations.json)")
    ap.add_argument("--log", default=None,
                    help="parse a pytest --durations=0 output log "
                         "instead of the recording")
    ap.add_argument("--budget", type=float,
                    default=float(os.environ.get(
                        "BYTEPS_TPU_TEST_BUDGET_S") or DEFAULT_BUDGET_S),
                    help="per-test seconds allowed (default 60; env "
                         "BYTEPS_TPU_TEST_BUDGET_S overrides)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as one JSON object")
    args = ap.parse_args(argv)
    if args.budget <= 0:
        print("check_test_budget: --budget must be > 0", file=sys.stderr)
        return 2
    if args.log:
        try:
            with open(args.log) as f:
                durations = parse_durations_log(f.read())
        except OSError as e:
            print(f"check_test_budget: cannot read {args.log}: {e}",
                  file=sys.stderr)
            return 2
    else:
        durations = load_recorded(args.path)
        if durations is None:
            print("check_test_budget: no durations recorded yet "
                  f"({args.path}) — nothing to check")
            return 0
    report = check(durations, budget_s=args.budget)
    if args.json:
        print(json.dumps(report))
    else:
        print(render(report))
    return 1 if report["offenders"] else 0


if __name__ == "__main__":
    sys.exit(main())
