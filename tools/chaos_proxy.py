#!/usr/bin/env python
"""Programmable TCP chaos proxy for PS-transport fault injection.

A thin forwarder between a `PSSession` and a real PS server that can
inject the transport faults a long-running TPU job actually sees —
connection resets mid-payload, silent blackholes, added latency, flapping
links — deterministically and in-process, so tests drive the *real*
client/server wire code through a fault instead of mocking sockets.

    proxy = ChaosProxy("127.0.0.1", server_port)
    proxy.start()
    sess = PSSession(["127.0.0.1"], [proxy.port], ...)   # via the proxy
    ...
    proxy.reset_after(4096)        # RST both sides after 4 KiB upstream
    proxy.blackhole(True)          # swallow everything, answer nothing
    proxy.kill_connections()       # drop every live conn right now
    proxy.kill_permanently()       # drop AND refuse all future conns —
                                   #   the peer is gone for good
    proxy.pass_through()           # clear all faults

Faults are **one-shot** by default (fire once, then the link heals —
the reconnect-and-replay scenario); `once=False` makes them **flapping**
(every new connection trips the same fault — the give-up scenario).

Also runs standalone for manual chaos testing:

    python tools/chaos_proxy.py --upstream 127.0.0.1:9001 \
        --listen-port 9101 --reset-after 65536 --flap
"""

from __future__ import annotations

import argparse
import socket
import struct
import threading
import time
from typing import Optional

_CHUNK = 65536


class _Fault:
    """One armed fault: kind in {'reset', 'drop'}, triggered after the
    proxy has forwarded `after_bytes` upstream-bound bytes (0 = on the
    next byte)."""

    def __init__(self, kind: str, after_bytes: int, once: bool):
        self.kind = kind
        self.after_bytes = int(after_bytes)
        self.once = once


class ChaosProxy:
    """A programmable TCP forwarder (see module docstring)."""

    def __init__(self, upstream_host: str, upstream_port: int,
                 listen_host: str = "127.0.0.1", listen_port: int = 0):
        self.upstream = (upstream_host, int(upstream_port))
        self._listen_addr = (listen_host, int(listen_port))
        self._lsock: Optional[socket.socket] = None
        self.port: int = 0
        self._lock = threading.Lock()
        self._fault: Optional[_Fault] = None
        self._delay_s = 0.0
        self._blackhole = False
        self._refuse = False
        self._closing = False
        self._conns: list = []        # [(client_sock, server_sock)]
        self._accept_thread: Optional[threading.Thread] = None
        # Counters (read via stats()).
        self._bytes_up = 0            # client -> server, forwarded
        self._bytes_down = 0          # server -> client, forwarded
        self._bytes_eaten = 0         # swallowed by blackhole
        self._connections = 0
        self._connections_refused = 0
        self._faults_fired = 0

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ChaosProxy":
        self._lsock = socket.socket()
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind(self._listen_addr)
        self._lsock.listen(64)
        self.port = self._lsock.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="chaos-accept")
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        with self._lock:
            self._closing = True
        if self._lsock is not None:
            try:
                self._lsock.close()
            except OSError:
                pass
        self.kill_connections(rst=False)

    def __enter__(self) -> "ChaosProxy":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- fault programming --------------------------------------------------
    def reset_after(self, nbytes: int = 0, once: bool = True) -> None:
        """RST both legs after `nbytes` further upstream-bound bytes — the
        mid-payload connection-reset fault (SO_LINGER 0 close)."""
        with self._lock:
            self._fault = _Fault("reset", nbytes, once)

    def drop_after(self, nbytes: int = 0, once: bool = True) -> None:
        """Cleanly FIN both legs after `nbytes` further upstream-bound
        bytes — the peer-went-away fault."""
        with self._lock:
            self._fault = _Fault("drop", nbytes, once)

    def kill_after_bytes(self, nbytes: int = 0) -> None:
        """Sever this target PERMANENTLY after `nbytes` further
        upstream-bound bytes: forward exactly that prefix (so the cut
        lands mid-frame, not politely on a frame boundary), RST every
        live leg, and refuse all future dials — the SIGKILL that lands
        partway through a replication/migration state transfer.  The
        receiver of the torn transfer must discard it whole (the wire's
        length-prefixed framing never dispatches a partial frame), so
        handoff is adopt-whole-or-discard, never torn.  pass_through()
        undoes the refusal (replacement hardware)."""
        with self._lock:
            self._fault = _Fault("kill", nbytes, True)

    def delay(self, ms: float) -> None:
        """Add per-chunk latency in both directions (crude WAN emulation)."""
        with self._lock:
            self._delay_s = max(0.0, ms) / 1000.0

    def blackhole(self, enabled: bool = True) -> None:
        """Swallow all traffic silently in both directions: bytes are read
        and discarded, nothing is forwarded, no error is surfaced — the
        stall fault a watchdog exists for.  Applies to live and new
        connections until disabled."""
        with self._lock:
            self._blackhole = enabled

    def refuse_new(self, enabled: bool = True) -> None:
        """Refuse (RST) every NEW connection while enabled.  Live
        connections keep flowing — combine with kill_connections() for a
        full outage (see kill_permanently)."""
        with self._lock:
            self._refuse = enabled

    def kill_permanently(self) -> None:
        """Drop every live connection AND refuse all future ones: the
        peer behind this proxy is gone for good (permanent worker loss /
        decommissioned host), vs kill_connections()'s transient outage
        where a reconnect succeeds.  What elastic-eviction tests use to
        prove the job survives a worker that is never coming back.
        pass_through() undoes it (the 'replacement hardware' scenario)."""
        self.refuse_new(True)
        self.kill_connections()

    def pass_through(self) -> None:
        """Clear every armed fault (delay, blackhole, reset/drop,
        refuse-new)."""
        with self._lock:
            self._fault = None
            self._delay_s = 0.0
            self._blackhole = False
            self._refuse = False

    def kill_connections(self, rst: bool = True) -> None:
        """Immediately drop every live proxied connection (RST by default);
        new connections keep working — the transient-outage fault."""
        with self._lock:
            conns, self._conns = self._conns, []
        for pair in conns:
            for s in pair:
                self._hard_close(s, rst)

    def stats(self) -> dict:
        with self._lock:
            return {
                "connections": self._connections,
                "connections_refused": self._connections_refused,
                "live_connections": len(self._conns),
                "bytes_up": self._bytes_up,
                "bytes_down": self._bytes_down,
                "bytes_eaten": self._bytes_eaten,
                "faults_fired": self._faults_fired,
            }

    # -- data path ----------------------------------------------------------
    def _accept_loop(self) -> None:
        while True:
            try:
                client, _ = self._lsock.accept()
            except OSError:
                return          # listener closed
            with self._lock:
                if self._closing:
                    client.close()
                    return
                if self._refuse:
                    # Permanent-kill mode: the peer is gone — every dial
                    # gets an immediate RST, so reconnect loops burn their
                    # backoff budget instead of finding a healed link.
                    self._connections_refused += 1
                    refuse = True
                else:
                    refuse = False
                    self._connections += 1
                    hole = self._blackhole
            if refuse:
                self._hard_close(client, rst=True)
                continue
            if hole:
                # Accept but never dial upstream: the connection looks
                # alive to the client while everything it sends vanishes.
                threading.Thread(target=self._swallow, args=(client,),
                                 daemon=True, name="chaos-swallow").start()
                continue
            try:
                server = socket.create_connection(self.upstream, timeout=30)
            except OSError:
                client.close()
                continue
            for s in (client, server):
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.append((client, server))
            threading.Thread(target=self._pump, args=(client, server, True),
                             daemon=True, name="chaos-up").start()
            threading.Thread(target=self._pump, args=(server, client, False),
                             daemon=True, name="chaos-down").start()

    def _swallow(self, sock: socket.socket) -> None:
        with self._lock:
            self._conns.append((sock,))
        try:
            while True:
                data = sock.recv(_CHUNK)
                if not data:
                    return
                with self._lock:
                    self._bytes_eaten += len(data)
        except OSError:
            return
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _pump(self, src: socket.socket, dst: socket.socket,
              upstream: bool) -> None:
        try:
            while True:
                data = src.recv(_CHUNK)
                if not data:
                    break
                with self._lock:
                    delay = self._delay_s
                    hole = self._blackhole
                    fault = self._fault
                    fire, cut = None, 0
                    if upstream and fault is not None:
                        if fault.after_bytes < len(data):
                            # Fires INSIDE this chunk: forward the prefix
                            # so the break lands mid-payload, not politely
                            # on a frame boundary.
                            fire, cut = fault.kind, fault.after_bytes
                            self._faults_fired += 1
                            if fault.kind == "kill":
                                # The peer dies WITH the torn transfer:
                                # no future dial may find it healed.
                                self._refuse = True
                            if fault.once:
                                self._fault = None
                            else:
                                fault.after_bytes = 0
                        else:
                            fault.after_bytes -= len(data)
                if delay:
                    time.sleep(delay)
                if hole:
                    with self._lock:
                        self._bytes_eaten += len(data)
                    continue    # keep reading, forward nothing
                if fire is not None:
                    if cut:
                        try:
                            dst.sendall(data[:cut])
                        except OSError:
                            pass
                    self._kill_pair(src, dst, rst=(fire != "drop"))
                    if fire == "kill":
                        # Every OTHER live connection to this target dies
                        # too — a SIGKILLed process takes all its sockets.
                        self.kill_connections()
                    return
                dst.sendall(data)
                with self._lock:
                    if upstream:
                        self._bytes_up += len(data)
                    else:
                        self._bytes_down += len(data)
        except OSError:
            pass
        finally:
            # Half-close propagation: a dead leg takes the pair with it
            # (the PS wire is request/response — a one-legged conn only
            # wedges the client).
            self._kill_pair(src, dst, rst=False)

    def _kill_pair(self, a: socket.socket, b: socket.socket,
                   rst: bool) -> None:
        with self._lock:
            self._conns = [pair for pair in self._conns
                           if a not in pair and b not in pair]
        for s in (a, b):
            self._hard_close(s, rst)

    @staticmethod
    def _hard_close(s: socket.socket, rst: bool) -> None:
        """Close that actually lands while pump threads sit in recv():
        CPython defers the real close while another thread blocks on the
        socket, so shutdown() first — it wakes the pump, whose exit lets
        the close (and the SO_LINGER-0 RST) go out."""
        try:
            if rst:
                s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             struct.pack("ii", 1, 0))
        except OSError:
            pass
        try:
            s.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            s.close()
        except OSError:
            pass


class MultiChaosProxy:
    """N independent chaos proxies in ONE process — the server-tier
    counterpart of ChaosProxy.

    A server-failover chaos test fronts every PS server with a proxy so
    any one of them can be killed permanently; spawning a process per
    server made that O(N) interpreters for a 3-line need.  Each target
    keeps its own fault schedule:

        multi = MultiChaosProxy([("127.0.0.1", p) for p in ports]).start()
        sess  = PSSession(["127.0.0.1"] * 3, multi.ports, ...)
        multi.kill_permanently(1)      # server 1 is gone for good
        multi.restore(1)               # ...or comes back (new hardware)
        multi.stats()                  # per-target counter dicts

    Any per-target fault the single proxy offers is reachable through
    ``multi.proxy(i)``.
    """

    def __init__(self, upstreams, listen_host: str = "127.0.0.1"):
        self.proxies = [ChaosProxy(h, p, listen_host=listen_host)
                        for h, p in upstreams]

    @property
    def ports(self):
        return [p.port for p in self.proxies]

    def start(self) -> "MultiChaosProxy":
        for p in self.proxies:
            p.start()
        return self

    def stop(self) -> None:
        for p in self.proxies:
            p.stop()

    def __enter__(self) -> "MultiChaosProxy":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def proxy(self, i: int) -> ChaosProxy:
        return self.proxies[i]

    def kill(self, i: int) -> None:
        """Transient outage of target i (reconnects succeed)."""
        self.proxies[i].kill_connections()

    def kill_permanently(self, i: int) -> None:
        """Target i is gone for good: drop and refuse forever."""
        self.proxies[i].kill_permanently()

    def kill_after_bytes(self, i: int, nbytes: int = 0) -> None:
        """Target i dies mid-frame after `nbytes` more upstream bytes
        (then refuses forever) — the torn-transfer SIGKILL."""
        self.proxies[i].kill_after_bytes(nbytes)

    def restore(self, i: int) -> None:
        """Heal target i (clear every armed fault)."""
        self.proxies[i].pass_through()

    def stats(self) -> list:
        return [p.stats() for p in self.proxies]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--upstream", required=True, metavar="HOST:PORT",
                    action="append",
                    help="real server address to forward to; repeat for "
                         "multi-target mode (one proxy per upstream, one "
                         "process total)")
    ap.add_argument("--listen-port", type=int, default=None,
                    action="append",
                    help="local port to listen on (0/omitted = ephemeral);"
                         " repeat to pair with repeated --upstream")
    ap.add_argument("--listen-host", default="127.0.0.1")
    ap.add_argument("--delay-ms", type=float, default=0.0,
                    help="per-chunk latency, both directions")
    ap.add_argument("--reset-after", type=int, default=None, metavar="N",
                    help="RST connections after N upstream bytes")
    ap.add_argument("--drop-after", type=int, default=None, metavar="N",
                    help="FIN connections after N upstream bytes")
    ap.add_argument("--kill-after", type=int, default=None, metavar="N",
                    help="RST mid-frame after N upstream bytes, then "
                         "refuse all future connections (torn-transfer "
                         "SIGKILL)")
    ap.add_argument("--blackhole", action="store_true",
                    help="swallow all traffic silently")
    ap.add_argument("--kill-permanent", action="store_true",
                    help="drop every connection and refuse all new ones "
                         "(the peer is gone for good)")
    ap.add_argument("--flap", action="store_true",
                    help="re-arm the reset/drop fault for every connection "
                         "(default: fire once, then heal)")
    args = ap.parse_args()
    upstreams = [u.rsplit(":", 1) for u in args.upstream]
    lports = args.listen_port or []
    proxies = []
    for i, (host, port) in enumerate(upstreams):
        lp = lports[i] if i < len(lports) else 0
        proxy = ChaosProxy(host, int(port), args.listen_host, lp)
        proxy.start()
        # The CLI fault schedule applies to EVERY target; per-target
        # schedules are an in-process (MultiChaosProxy) feature.
        if args.delay_ms:
            proxy.delay(args.delay_ms)
        if args.reset_after is not None:
            proxy.reset_after(args.reset_after, once=not args.flap)
        if args.drop_after is not None:
            proxy.drop_after(args.drop_after, once=not args.flap)
        if args.kill_after is not None:
            proxy.kill_after_bytes(args.kill_after)
        if args.blackhole:
            proxy.blackhole(True)
        if args.kill_permanent:
            proxy.kill_permanently()
        print(f"chaos proxy[{i}]: {args.listen_host}:{proxy.port} -> "
              f"{host}:{port}", flush=True)
        proxies.append(proxy)
    try:
        while True:
            time.sleep(5)
            for i, proxy in enumerate(proxies):
                print(f"chaos proxy[{i}] stats: {proxy.stats()}",
                      flush=True)
    except KeyboardInterrupt:
        for proxy in proxies:
            proxy.stop()
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
