#!/usr/bin/env python
"""PS-wire codec microbenchmark.

Three sections, all CPU-only (no JAX, no accelerator):

  1. codec throughput — raw encode/decode MB/s and compression ratio per
     wire codec (`server/wire.py`, riding the C codec when built);
  2. pipeline A/B — a multi-partition compressed push_pull through the
     real native PS server over loopback, codec pipeline ON
     (BYTEPS_TPU_COMPRESS_THREADS=N) vs the inline fallback
     (COMPRESS_THREADS=0, encode on the caller thread / decode on the
     receiver thread).  Headline: the CALLER-BLOCK wall time — how long
     the compressed push_pull holds the caller thread before it can
     overlap its own step compute (inline pays every partition's encode
     there; the pipeline hands it to pool threads and returns in ~ms).
     Full sync round-trips are reported alongside (see pipeline_ab's
     docstring for the colocated-server caveat on small hosts);
  3. fusion A/B — the many-small-tensors regime (hundreds of layernorm
     scales / biases): per-leaf push_pull (one declare/push/ack chain per
     leaf) vs the fusion-bucket layer (common/fusion.py packing small
     leaves into ~BYTEPS_TPU_FUSION_BYTES buckets dispatched through
     PSSession.push_pull_group in priority-descending order).  Reports
     wire messages, caller-block time, and sync-round time per mode.

Usage:
    python tools/wire_bench.py [--quick] [--json] [--threads N]
                               [--mb MB] [--part-kb KB] [--rounds R]
                               [--fusion-only] [--fusion-leaves N]

--json prints a machine-readable result document on stdout (progress
lines go to stderr); tests/test_wire_bench.py runs `--quick --json` as
the `-m slow` smoke invocation.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import statistics
import subprocess
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from byteps_tpu.server import wire                      # noqa: E402
from byteps_tpu.server.client import PSSession          # noqa: E402
from byteps_tpu.utils.hermetic import cpu_subprocess_env  # noqa: E402

# Codec set for the throughput section: the production wire formats.
_CODECS = [
    ("onebit", {"compressor": "onebit"}),
    ("onebit+ef", {"compressor": "onebit", "ef": "vanilla"}),
    ("dithering-dense", {"compressor": "dithering", "k": "15"}),
    ("dithering-elias", {"compressor": "dithering", "k": "15",
                         "coding": "elias"}),
    ("topk", {"compressor": "topk", "k": "4096"}),
    ("qblock8", {"compressor": "qblock", "bits": "8", "block": "256"}),
    ("qblock4+ef", {"compressor": "qblock", "bits": "4", "block": "256",
                    "ef": "vanilla"}),
]

# The adaptive-compression dial (common/tuner.py DIAL) for --codec-sweep:
# the sweep is the tuner's cost-model ground truth — per-codec
# encode/decode throughput and compression ratio across the real
# partition-size range, so the dial's "step harder under wire pressure"
# direction can be sanity-checked against measured numbers.
_SWEEP_CODECS = [
    ("onebit+ef", {"compressor": "onebit", "ef": "vanilla"}),
    ("elias+ef", {"compressor": "dithering", "k": "15",
                  "coding": "elias", "ef": "vanilla"}),
    ("qblock8+ef", {"compressor": "qblock", "bits": "8", "block": "256",
                    "ef": "vanilla"}),
    ("qblock4+ef", {"compressor": "qblock", "bits": "4", "block": "256",
                    "ef": "vanilla"}),
]


def codec_sweep(sizes_bytes, reps: int) -> list:
    """Per-(codec, size) encode/decode throughput + ratio table — the
    tuner's cost-model seed (``--codec-sweep``).  Sizes are partition
    payload bytes (f32 elements = bytes/4), spanning the fusion floor
    (64 KiB) to the 16 MiB receive-pool ceiling."""
    out = []
    for nbytes in sizes_bytes:
        n = nbytes // 4
        x = _gradient(n)
        raw_row = {"codec": "raw", "size_bytes": nbytes,
                   "encode_MBps": None, "decode_MBps": None, "ratio": 1.0}
        out.append(raw_row)
        for name, kw in _SWEEP_CODECS:
            wc = wire.WireCompressor(dict(kw))
            blob = wc.encode(1, x)                 # warm (+ EF state)
            t0 = time.perf_counter()
            for _ in range(reps):
                blob = wc.encode(1, x)
            enc = (time.perf_counter() - t0) / reps
            wire.decode(blob, n)                   # warm
            t0 = time.perf_counter()
            for _ in range(reps):
                wire.decode(blob, n)
            dec = (time.perf_counter() - t0) / reps
            row = {
                "codec": name,
                "size_bytes": nbytes,
                "encode_MBps": round(x.nbytes / enc / 1e6, 1),
                "decode_MBps": round(x.nbytes / dec / 1e6, 1),
                "ratio": round(x.nbytes / len(blob), 2),
                "wire_bytes": len(blob),
                "native": wire._c_wire() is not None,
            }
            out.append(row)
            _log(f"  {nbytes >> 10:6d} KiB  {name:12s} "
                 f"enc {row['encode_MBps']:8.1f} MB/s   "
                 f"dec {row['decode_MBps']:8.1f} MB/s   "
                 f"{row['ratio']:6.1f}x")
    return out


def sparse_sweep(table_rows: int, widths, densities, reps: int) -> list:
    """Per-(width, density) row-sparse block codec table — encode/decode
    rows/s and the index-codec ratio (``--sparse-sweep``).

    The row-sparse plane ships ``(indices, rows)`` blocks
    (wire.encode_sparse_block: 16-byte header + index stream + f32
    rows); the index stream picks elias-delta over gaps when strictly
    smaller than raw u32 LE.  This sweep answers the sizing questions
    docs/sparse-embedding.md points at: how many rows/s one core can
    frame at each embedding width, and how much the gap codec saves at
    recsys densities (sorted-unique zipfian-ish indices, where dense
    regions give small gaps)."""
    out = []
    rng = np.random.RandomState(7)
    for width in widths:
        for density in densities:
            nrows = max(1, int(table_rows * density))
            # Sorted-unique draw — the shape push_pull_sparse ships
            # after client-side coalescing (np.unique output).
            idx = np.unique(rng.choice(table_rows, size=nrows,
                                       replace=False).astype(np.uint32))
            rows = rng.randn(idx.size, width).astype(np.float32)
            blob = wire.encode_sparse_block(idx, rows, width)   # warm
            t0 = time.perf_counter()
            for _ in range(reps):
                blob = wire.encode_sparse_block(idx, rows, width)
            enc = (time.perf_counter() - t0) / reps
            wire.decode_sparse_block(blob)                      # warm
            t0 = time.perf_counter()
            for _ in range(reps):
                wire.decode_sparse_block(blob)
            dec = (time.perf_counter() - t0) / reps
            codec, stream = wire.encode_sparse_indices(idx)
            raw_idx = idx.size * 4
            row = {
                "width": width,
                "density": density,
                "nrows": int(idx.size),
                "encode_rows_per_s": round(idx.size / enc, 1),
                "decode_rows_per_s": round(idx.size / dec, 1),
                "wire_bytes": len(blob),
                "idx_codec": ("elias"
                              if codec == wire.SPARSE_CODEC_ELIAS
                              else "raw"),
                "idx_codec_ratio": round(
                    raw_idx / max(1, len(stream) or raw_idx), 3),
                "dense_ratio": round(table_rows * width * 4
                                     / len(blob), 1),
            }
            out.append(row)
            _log(f"  w={width:5d} d={density * 100:5.1f}% "
                 f"({idx.size:6d} rows)  "
                 f"enc {row['encode_rows_per_s'] / 1e6:7.2f} Mrow/s  "
                 f"dec {row['decode_rows_per_s'] / 1e6:7.2f} Mrow/s  "
                 f"idx={row['idx_codec']:5s} "
                 f"{row['idx_codec_ratio']:5.2f}x  "
                 f"vs-dense {row['dense_ratio']:7.1f}x")
    return out


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _gradient(n: int, seed: int = 1) -> np.ndarray:
    """Heavy-tailed sparse-ish gradient (the regime real training ships:
    most dithering levels quantize to 0, so elias has gaps to code)."""
    rng = np.random.RandomState(seed)
    return (rng.randn(n) * (rng.rand(n) < 0.2)).astype(np.float32)


def codec_throughput(n: int, reps: int) -> list:
    out = []
    x = _gradient(n)
    for name, kw in _CODECS:
        wc = wire.WireCompressor(dict(kw))
        blob = wc.encode(1, x)                     # warm (+ EF state)
        t0 = time.perf_counter()
        for _ in range(reps):
            blob = wc.encode(1, x)
        enc = (time.perf_counter() - t0) / reps
        wire.decode(blob, n)                       # warm
        t0 = time.perf_counter()
        for _ in range(reps):
            wire.decode(blob, n)
        dec = (time.perf_counter() - t0) / reps
        row = {
            "codec": name,
            "encode_MBps": round(x.nbytes / enc / 1e6, 1),
            "decode_MBps": round(x.nbytes / dec / 1e6, 1),
            "ratio": round(x.nbytes / len(blob), 2),
            "native": wire._c_wire() is not None,
        }
        out.append(row)
        _log(f"  {name:17s} enc {row['encode_MBps']:8.1f} MB/s   "
             f"dec {row['decode_MBps']:8.1f} MB/s   {row['ratio']:5.1f}x")
    return out


def boot_server(extra_env=None):
    """Native PS server subprocess on a freshly-probed port (the bind
    race retry pattern of bench.py bench_ps)."""
    import tempfile
    for _ in range(4):
        with socket.socket() as sk:
            sk.bind(("127.0.0.1", 0))
            port = sk.getsockname()[1]
        env = cpu_subprocess_env({
            "DMLC_PS_ROOT_PORT": str(port - 1),
            "DMLC_NUM_WORKER": "1",
            "BYTEPS_SERVER_ENGINE_THREAD": str(min(4, os.cpu_count() or 4)),
            **(extra_env or {}),
        })
        errf = tempfile.TemporaryFile(mode="w+")
        proc = subprocess.Popen(
            [sys.executable, "-m", "byteps_tpu.server"],
            env=env, stdout=subprocess.DEVNULL, stderr=errf)
        deadline = time.time() + 30
        while True:
            try:
                socket.create_connection(("127.0.0.1", port), 0.5).close()
                return proc, port
            except OSError:
                if proc.poll() is not None:
                    errf.seek(0)
                    stderr = errf.read()[-500:]
                    errf.close()
                    if "in use" not in stderr.lower():
                        raise RuntimeError(
                            f"PS server died at startup "
                            f"(rc={proc.returncode}): {stderr}")
                    break               # lost the port race — retry fresh
                if time.time() > deadline:
                    proc.kill()
                    proc.wait()
                    raise RuntimeError("PS server did not come up")
                time.sleep(0.1)
    raise RuntimeError("PS server lost the port race 4 times")


def measure_echo_floor(nbytes: int, reps: int,
                       uds_path: str = "") -> float:
    """Raw synchronous send+recv echo — the transport ceiling for a
    Python client on this host, measured over the SAME transport the PS
    session uses (loopback TCP, or AF_UNIX when ``uds_path`` is set):
    no protocol, no framing, no summing, no store.  Returns GB/s of
    2 * nbytes * reps (the echo moves each byte both ways)."""
    import threading

    if uds_path:
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        path = f"{uds_path}.echo.{os.getpid()}"
        try:
            os.unlink(path)
        except OSError:
            pass
        srv.bind(path)
        addr = path
    else:
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        addr = ("127.0.0.1", srv.getsockname()[1])
    srv.listen(1)

    def serve():
        c, _ = srv.accept()
        buf = bytearray(nbytes)
        view = memoryview(buf)
        for _ in range(reps + 1):
            got = 0
            while got < nbytes:
                r = c.recv_into(view[got:], nbytes - got)
                if r == 0:
                    return
                got += r
            c.sendall(buf)

    th = threading.Thread(target=serve, daemon=True)
    th.start()
    if uds_path:
        c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        c.connect(addr)
    else:
        c = socket.create_connection(addr)
    data = bytes(nbytes)
    out = bytearray(nbytes)
    oview = memoryview(out)

    def rt():
        c.sendall(data)
        got = 0
        while got < nbytes:
            got += c.recv_into(oview[got:], nbytes - got)

    rt()  # warm
    t0 = time.perf_counter()
    for _ in range(reps):
        rt()
    dt = time.perf_counter() - t0
    c.close()
    srv.close()
    if uds_path:
        try:
            os.unlink(path)
        except OSError:
            pass
    return 2 * nbytes * reps / dt / 1e9


def echo_floor_section(nbytes: int, part_bytes: int, reps: int,
                       uds: bool = False, wire_conns: int = 0) -> dict:
    """The ≥85%-of-wire-floor acceptance number, emitted by the bench
    instead of hand-calculated: raw-socket echo floor and full-PS raw
    push_pull goodput on the SAME host and transport, as a percentage.

    The PS goodput counts logical push+pull bytes (2 * tensor bytes per
    round) against wall time — the same accounting as the floor's
    send+recv — so pct_of_floor is exactly "how much of the achievable
    wire rate the full KV semantics (partitioned, summed, round-tracked)
    sustain"."""
    uds_path = f"/tmp/bps_wire_bench_{os.getpid()}" if uds else ""
    batches = 4
    batch_reps = max(2, reps // batches)
    _log(f"  echo floor ({nbytes / 1e6:.0f} MB, {batches} interleaved "
         f"batches x {batch_reps} reps, {'uds' if uds else 'tcp'}) ...")
    proc, port = boot_server(
        {"BYTEPS_TPU_SERVER_UDS": uds_path} if uds else None)
    try:
        kw = {"wire_conns": wire_conns} if wire_conns else {}
        sess = PSSession(["127.0.0.1"], [port], worker_id=0, num_servers=1,
                         partition_bytes=part_bytes,
                         uds_path=uds_path, **kw)
        transports = sorted({c.transport
                             for pool in sess._data_conns for c in pool})
        x = np.random.default_rng(0).standard_normal(
            nbytes // 4).astype(np.float32)
        sess.push_pull(1, x)               # init + warm
        # INTERLEAVED best-of batches: on shared/small hosts the floor
        # itself swings ~2x with CPU-frequency and neighbor noise, so a
        # single floor-then-PS sequence reports whatever the host was
        # doing that second.  Alternating short batches and taking each
        # side's best compares like with like.
        floors, goods = [], []
        for _ in range(batches):
            floors.append(measure_echo_floor(nbytes, batch_reps,
                                             uds_path=uds_path))
            t0 = time.perf_counter()
            for _ in range(batch_reps):
                sess.push_pull(1, x)
            goods.append(2 * x.nbytes * batch_reps
                         / (time.perf_counter() - t0) / 1e9)
        floor, goodput = max(floors), max(goods)
        stats = sess.server_stats()
        tstats = sess.transport_stats()
        sess.close()
    finally:
        proc.kill()
        proc.wait()
    row = {
        "transport": "+".join(transports),
        "tensor_mb": round(nbytes / 1e6, 1),
        "partitions": (nbytes + part_bytes - 1) // part_bytes,
        "reps": batches * batch_reps,
        "floor_gbps": round(floor, 3),
        "floor_batches_gbps": [round(f, 3) for f in floors],
        "goodput_gbps": round(goodput, 3),
        "goodput_batches_gbps": [round(g, 3) for g in goods],
        "pct_of_floor": round(100.0 * goodput / floor, 1),
        "target_pct_of_floor": 85.0,
        "scatter_frames": stats.get("scatter_frames", 0),
        "pool_hits": tstats["pool_hits"],
    }
    _log(f"  {row['transport']:8s} floor {row['floor_gbps']:6.2f} GB/s   "
         f"PS {row['goodput_gbps']:6.2f} GB/s   "
         f"pct_of_floor {row['pct_of_floor']:5.1f}%")
    return row


def _timed_rounds(sess, key, data, rounds: int):
    """(caller_block, sync_round) second-pairs per round.

    caller_block = the push_pull_async() call's own duration: how long
    the CALLER thread is captive to codec work before it can go do the
    training step's compute.  sync_round = issue + wait, the full
    round-trip."""
    sess.push_pull(key, data)          # warm: INITs + first merge
    out = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        h = sess.push_pull_async(key, data)
        t1 = time.perf_counter()
        h.wait()
        out.append((t1 - t0, time.perf_counter() - t0))
    return out


def pipeline_ab(nbytes: int, part_bytes: int, rounds: int,
                threads: int, kw: dict) -> dict:
    """Compressed multi-partition push_pull, codec pipeline vs inline.

    Headline (`inline_s`/`pipelined_s`): best-of caller-block wall time —
    the wall time a compressed push_pull holds the CALLER thread, which
    is what the pipeline exists to remove (inline mode encodes every
    partition before push_pull_async returns; a training loop pays that
    serially against its step compute every iteration).  Best-of because
    shared hosts put noisy-neighbor stalls in the tail of both modes.

    `sync_round` (reported alongside): the full issue+wait round trip.
    NOTE an honest caveat: with the PS server COLOCATED on a small host
    (this bench's only option), total CPU is the binding resource, so
    overlapping encode with the server's merge buys little and the
    thread interleaving costs a few percent — parity-ish sync rounds
    here.  The overlap pays on deployment shapes: server on separate
    hardware, or workers with idle cores for the pool.
    """
    data = _gradient(nbytes // 4, seed=2)
    proc, port = boot_server()
    try:
        res = {}
        # Pipelined first, then inline: if anything, the later run enjoys
        # the warmer page cache, biasing AGAINST the pipeline claim.
        for label, ct, key in (("pipelined", threads, 7), ("inline", 0, 8)):
            s = PSSession(["127.0.0.1"], [port], worker_id=0, num_servers=1,
                          partition_bytes=part_bytes, min_compress_bytes=0,
                          compress_threads=ct)
            s.register_compressor(key, dict(kw))
            times = _timed_rounds(s, key, data, rounds)
            blocks = [b for b, _ in times]
            syncs = [r for _, r in times]
            res[label] = {
                "caller_block_best_s": round(min(blocks), 5),
                "caller_block_median_s": round(
                    statistics.median(blocks), 5),
                "sync_round_best_s": round(min(syncs), 4),
                "sync_round_median_s": round(statistics.median(syncs), 4),
                "compress_threads": ct,
                **{k: v for k, v in s.codec_stats().items()
                   if k in ("encoded_parts", "decoded_parts",
                            "encode_busy_us", "decode_busy_us")},
            }
            s.close()
            r = res[label]
            _log(f"  {label:10s} (threads={ct}) caller-block best "
                 f"{r['caller_block_best_s'] * 1e3:7.2f} ms   sync round "
                 f"best {r['sync_round_best_s'] * 1e3:7.2f} ms  median "
                 f"{r['sync_round_median_s'] * 1e3:7.2f} ms")
        blk_i = res["inline"]["caller_block_best_s"]
        blk_p = res["pipelined"]["caller_block_best_s"]
        return {
            "tensor_mb": nbytes / 1e6,
            "partitions": (nbytes + part_bytes - 1) // part_bytes,
            "compressor": dict(kw),
            "rounds": rounds,
            "stat": "caller_block_best",
            "inline_s": blk_i,
            "pipelined_s": blk_p,
            "speedup": round(blk_i / blk_p, 2) if blk_p else 0.0,
            **res,
        }
    finally:
        proc.kill()
        proc.wait()


def fusion_ab(num_leaves: int, min_kb: int, max_kb: int, rounds: int,
              fusion_bytes: int) -> dict:
    """Many-small-tensors A/B: per-leaf push_pull vs fused buckets.

    The regime the fusion layer exists for: `num_leaves` gradients of
    min_kb-max_kb each (a transformer's layernorm scales and biases).
    Unfused, every leaf pays its own declare/push/ack chain — per-message
    overhead dominates at these sizes.  Fused, the planner packs them
    into ~fusion_bytes buckets, each riding ONE partition key through
    push_pull_group at the max member priority.

    Reported per mode: wire messages per round (PUSH dispatches; PULLs
    mirror them 1:1), caller-block wall time (issue-all duration — what
    the training loop pays before it can overlap its own compute; the
    fused figure honestly includes the bucket packing), and the full
    sync round.  `priority_descending` asserts the fused dispatch order
    the trace spans show: bucket 0 (last-layer grads) first.
    """
    from byteps_tpu.common import fusion

    rng = np.random.RandomState(3)
    sizes = [int(n) for n in rng.randint(
        min_kb * 1024 // 4, max_kb * 1024 // 4 + 1, num_leaves)]
    leaves = [rng.randn(n).astype(np.float32) for n in sizes]
    total_mb = sum(sizes) * 4 / 1e6
    proc, port = boot_server()
    try:
        res = {}
        s = PSSession(["127.0.0.1"], [port], worker_id=0, num_servers=1)

        # ---- unfused: one key chain per leaf, per-leaf priorities.
        base = 1000
        for i, l in enumerate(leaves):      # warm: INITs + first merge
            s.push_pull(base + i, l, priority=i)
        s.push_order = []
        s.record_push_order = True
        blocks, syncs = [], []
        for _ in range(rounds):
            t0 = time.perf_counter()
            hs = [s.push_pull_async(base + i, leaves[i], priority=i)
                  for i in range(num_leaves)]
            t1 = time.perf_counter()
            for h in hs:
                h.wait()
            blocks.append(t1 - t0)
            syncs.append(time.perf_counter() - t0)
        s.record_push_order = False
        res["unfused"] = {
            "wire_messages_per_round": len(s.push_order) // rounds,
            "caller_block_best_s": round(min(blocks), 5),
            "caller_block_median_s": round(statistics.median(blocks), 5),
            "sync_round_best_s": round(min(syncs), 4),
            "sync_round_median_s": round(statistics.median(syncs), 4),
        }

        # ---- fused: planner buckets through grouped staging.
        plan = fusion.plan_buckets(
            tuple((i, sizes[i], "float32", 4) for i in range(num_leaves)),
            fusion_bytes)
        bkey = {b.index: 2000 + b.index for b in plan.buckets}
        prio_of_key = {bkey[b.index]: b.priority for b in plan.buckets}
        solo_items = [(3000 + li, li) for li, _ in plan.solo]
        prio_of_key.update({k: p for k, p in solo_items})

        def build_items():
            items = [(bkey[b.index],
                      np.concatenate([leaves[li] for li, _ in b.members])
                      if len(b.members) > 1 else leaves[b.members[0][0]],
                      b.priority) for b in plan.buckets]
            items += [(k, leaves[li], p)
                      for (k, p), (li, _) in zip(solo_items, plan.solo)]
            items.sort(key=lambda it: -it[2])
            return items

        for h in s.push_pull_group(build_items()):    # warm
            h.wait()
        s.push_order = []
        s.record_push_order = True
        blocks, syncs = [], []
        for _ in range(rounds):
            t0 = time.perf_counter()
            hs = s.push_pull_group(build_items())
            t1 = time.perf_counter()
            for h in hs:
                h.wait()
            blocks.append(t1 - t0)
            syncs.append(time.perf_counter() - t0)
        s.record_push_order = False
        first_round = s.push_order[:len(s.push_order) // rounds]
        prios = [prio_of_key.get(pk >> 16, -1) for pk in first_round]
        res["fused"] = {
            "wire_messages_per_round": len(s.push_order) // rounds,
            "caller_block_best_s": round(min(blocks), 5),
            "caller_block_median_s": round(statistics.median(blocks), 5),
            "sync_round_best_s": round(min(syncs), 4),
            "sync_round_median_s": round(statistics.median(syncs), 4),
            "buckets": len(plan.buckets),
            "solo_leaves": len(plan.solo),
        }
        s.close()
        uf, fu = res["unfused"], res["fused"]
        for label, r in res.items():
            _log(f"  {label:8s} msgs/round {r['wire_messages_per_round']:4d}"
                 f"   caller-block best "
                 f"{r['caller_block_best_s'] * 1e3:8.2f} ms   sync best "
                 f"{r['sync_round_best_s'] * 1e3:8.2f} ms")
        return {
            "num_leaves": num_leaves,
            "leaf_kb": [min_kb, max_kb],
            "total_mb": round(total_mb, 2),
            "fusion_bytes": fusion_bytes,
            "rounds": rounds,
            "wire_message_reduction": round(
                uf["wire_messages_per_round"]
                / max(1, fu["wire_messages_per_round"]), 2),
            "caller_block_speedup": round(
                uf["caller_block_best_s"]
                / max(1e-9, fu["caller_block_best_s"]), 2),
            "sync_round_speedup": round(
                uf["sync_round_best_s"]
                / max(1e-9, fu["sync_round_best_s"]), 2),
            "priority_descending": all(
                a >= b for a, b in zip(prios, prios[1:])),
            **res,
        }
    finally:
        proc.kill()
        proc.wait()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="small sizes / few reps (CI smoke)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable results on stdout")
    ap.add_argument("--threads", type=int, default=2,
                    help="codec pipeline width for the A/B (default 2)")
    ap.add_argument("--mb", type=float, default=None,
                    help="tensor size for the A/B in MB")
    ap.add_argument("--part-kb", type=int, default=None,
                    help="partition size in KB")
    ap.add_argument("--rounds", type=int, default=None,
                    help="timed push_pull rounds per mode")
    ap.add_argument("--fusion-only", action="store_true",
                    help="run only the many-small-tensors fusion A/B")
    ap.add_argument("--echo-floor", action="store_true",
                    help="run only the raw-speed section: raw socket echo "
                         "floor vs full-PS raw push_pull goodput on the "
                         "same transport, reported as pct_of_floor "
                         "(target >= 85)")
    ap.add_argument("--uds", action="store_true",
                    help="with --echo-floor: measure the AF_UNIX fast "
                         "path (floor AND PS session both ride UDS)")
    ap.add_argument("--wire-conns", type=int, default=0,
                    help="with --echo-floor: lane count override "
                         "(default: session default)")
    ap.add_argument("--no-fusion", action="store_true",
                    help="skip the fusion A/B (codec/pipeline sections "
                         "only, the pre-fusion bench surface)")
    ap.add_argument("--fusion-leaves", type=int, default=None,
                    help="leaf count for the fusion A/B (default 512, "
                         "128 with --quick)")
    ap.add_argument("--codec-sweep", action="store_true",
                    help="run only the per-codec encode/decode "
                         "throughput + ratio sweep across partition "
                         "sizes (64 KiB - 16 MiB) — the adaptive-"
                         "compression tuner's cost-model ground truth")
    ap.add_argument("--sparse-sweep", action="store_true",
                    help="run only the row-sparse block codec sweep: "
                         "encode/decode rows/s and index-codec ratio "
                         "across embedding widths 32-1024 and touched "
                         "densities 0.1%%-10%% "
                         "(docs/sparse-embedding.md)")
    args = ap.parse_args(argv)

    quick = args.quick
    n_codec = (1 << 18) if quick else (1 << 21)
    reps = 3 if quick else 10
    mb = args.mb if args.mb is not None else (8.0 if quick else 32.0)
    part_kb = args.part_kb or (512 if quick else 1024)
    rounds = args.rounds or (9 if quick else 15)

    if args.sparse_sweep:
        table_rows = 1 << 17 if quick else 1 << 20
        widths = [32, 256] if quick else [32, 64, 128, 256, 512, 1024]
        densities = ([0.001, 0.1] if quick
                     else [0.001, 0.003, 0.01, 0.03, 0.1])
        sweep_reps = 2 if quick else 5
        _log(f"wire_bench: sparse sweep ({table_rows} table rows, "
             f"{len(widths)} widths x {len(densities)} densities, "
             f"{sweep_reps} reps)")
        sweep = sparse_sweep(table_rows, widths, densities, sweep_reps)
        doc = {"sparse_sweep": sweep,
               "config": {"quick": quick, "table_rows": table_rows,
                          "cpus": os.cpu_count()}}
        if args.json:
            print(json.dumps(doc, indent=1))
        return 0

    if args.codec_sweep:
        sizes = ([64 << 10, 1 << 20] if quick
                 else [64 << 10, 256 << 10, 1 << 20, 4 << 20, 16 << 20])
        sweep_reps = 2 if quick else 5
        _log(f"wire_bench: codec sweep ({len(sizes)} sizes x "
             f"{len(_SWEEP_CODECS)} codecs, {sweep_reps} reps)")
        sweep = codec_sweep(sizes, sweep_reps)
        doc = {"codec_sweep": sweep,
               "config": {"quick": quick, "cpus": os.cpu_count(),
                          "native": wire._c_wire() is not None}}
        if args.json:
            # Persist the table machine-readable at the STABLE path the
            # predictive tuner seeds from (BYTEPS_TPU_KNOB_COST_MODEL,
            # default ~/.cache/byteps_tpu/codec_cost_model.json) — the
            # producer half of the cost-model contract.  Atomic rename
            # so a tuner loading mid-write never sees a torn file.
            from byteps_tpu.common.tuner import cost_model_path
            path = cost_model_path()
            try:
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(doc, f, indent=1)
                os.replace(tmp, path)
                doc["cost_model_path"] = path
                _log(f"wire_bench: cost model written to {path}")
            except OSError as e:
                _log(f"wire_bench: cost model NOT persisted: {e}")
            print(json.dumps(doc, indent=1))
        return 0

    if args.echo_floor:
        # The acceptance workload: 4 MiB partitions, raw f32, same-host
        # echo floor on the same transport.  16 MB tensor under --quick
        # keeps the CI smoke short; 64 MB otherwise (the bench_ps shape).
        ef_bytes = (16 << 20) if quick else (64 << 20)
        ef_reps = args.rounds or (5 if quick else 15)
        _log(f"wire_bench: echo floor vs PS goodput "
             f"({ef_bytes >> 20} MB, 4 MiB partitions, {ef_reps} reps)")
        ef = echo_floor_section(ef_bytes, 4 << 20, ef_reps, uds=args.uds,
                                wire_conns=args.wire_conns)
        doc = {"echo_floor": ef,
               "config": {"quick": quick, "cpus": os.cpu_count()}}
        if args.json:
            print(json.dumps(doc, indent=1))
        return 0

    # Many-small-tensors fusion A/B (the transformer layernorm/bias tail):
    # 512 leaves of 4-64 KiB, fused at the 1 MiB default threshold.
    fus = None
    if not args.no_fusion:
        fus_leaves = args.fusion_leaves or (128 if quick else 512)
        fus_rounds = args.rounds or (5 if quick else 9)
        _log(f"wire_bench: fusion A/B ({fus_leaves} leaves of 4-64 KiB, "
             f"{fus_rounds} rounds)")
        fus = fusion_ab(fus_leaves, 4, 64, fus_rounds, 1 << 20)
        _log(f"  wire-message reduction "
             f"{fus['wire_message_reduction']:.1f}x   caller-block speedup "
             f"{fus['caller_block_speedup']:.1f}x   sync speedup "
             f"{fus['sync_round_speedup']:.1f}x   "
             f"priority_descending={fus['priority_descending']}")
    if args.fusion_only:
        doc = {"fusion": fus,
               "config": {"quick": quick, "cpus": os.cpu_count()}}
        if args.json:
            print(json.dumps(doc, indent=1))
        return 0

    _log(f"wire_bench: codec throughput ({n_codec} f32, {reps} reps)")
    codec = codec_throughput(n_codec, reps)

    # Encode-heavy codec for the headline A/B: elias dithering is the
    # reference's entropy coder and the costliest encoder in the set, the
    # regime the pipeline exists for.  No EF: the EF state lock would
    # serialize the pool's encoders (documented in docs/performance.md).
    ab_kw = {"compressor": "dithering", "k": "15", "coding": "elias"}
    _log(f"wire_bench: pipeline A/B ({mb:.0f} MB tensor, {part_kb} KB "
         f"partitions, {rounds} rounds, threads={args.threads})")
    pipeline = pipeline_ab(int(mb * 1e6), part_kb * 1024, rounds,
                           max(1, args.threads), ab_kw)
    _log(f"  caller-block speedup (inline/pipelined): "
         f"{pipeline['speedup']:.1f}x")

    # Bidirectional codec A/B: onebit's pull leg comes back re-compressed,
    # so this is the config that drives the DECODE half of the pipeline
    # (decoded_parts > 0 in the pipelined row proves the receiver thread
    # stayed codec-free); cheap codec, so the caller-block gap is smaller
    # — the elias A/B above stays the headline.
    bidi_kw = {"compressor": "onebit"}
    _log(f"wire_bench: bidirectional (decode-leg) A/B "
         f"({mb:.0f} MB tensor, onebit)")
    bidi = pipeline_ab(int(mb * 1e6), part_kb * 1024, rounds,
                       max(1, args.threads), bidi_kw)
    _log(f"  caller-block speedup (inline/pipelined): {bidi['speedup']:.1f}x"
         f"  decoded_parts={bidi['pipelined']['decoded_parts']}")

    doc = {"codec": codec, "pipeline": pipeline,
           "pipeline_bidirectional": bidi,
           **({"fusion": fus} if fus is not None else {}),
           "config": {"quick": quick, "threads": args.threads,
                      "cpus": os.cpu_count()}}
    if args.json:
        print(json.dumps(doc, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
