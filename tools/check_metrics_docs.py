#!/usr/bin/env python
"""check_metrics_docs — assert every exported bps_* metric is documented.

Every ``bps_*`` metric the code registers (``gauge(`` / ``counter(`` /
``histogram(`` / ``register_collector(`` calls anywhere under
``byteps_tpu/`` or ``tools/``) must have a row (or at least a mention)
in ``docs/monitoring.md`` — and every exact ``bps_*`` metric name that
document mentions must still be exported by the code.  Undocumented
metrics are how operators end up reading source to build dashboards,
and stale rows are how they alert on series that no longer exist; both
directions drift one PR at a time unless a test pins them.  The
companion of tools/check_env_docs.py (knobs) and
tools/check_doctor_docs.py (rule playbooks).

A doc mention ending in ``*`` (e.g. ``bps_codec_*``) covers every
exported name under that prefix — the collector-backed mirror families
are documented as families on purpose.

Wired as a fast tier-1 test (tests/test_metrics_docs.py); also runnable
standalone:

    python tools/check_metrics_docs.py [repo_root]

Exit 0 = in sync; 1 = drift (each missing name printed with where it
was seen).
"""

from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Set, Tuple

# A registration is the metric-name literal in first-argument position
# of a registry call; \s* after the paren rides call-site line breaks.
REG_RE = re.compile(
    r"(?:gauge|counter|histogram)\(\s*"
    r"[\"'](bps_[a-z0-9_]+)[\"']")

# A collector registers a NAME, and the snapshot synthesizes one series
# per stats key under it: register_collector("codec", ...) exports the
# bps_codec_* family.  Those dynamic names can only be documented (and
# checked) as a prefix family.
COLLECTOR_RE = re.compile(
    r"register_collector\(\s*[\"']([a-z0-9_]+)[\"']")

# Doc mentions: bare names plus the `bps_family_*` wildcard form.
DOC_RE = re.compile(r"bps_[a-z0-9_]+\*?")

# bps_*-shaped words that are not metric names: the tools themselves
# (their filenames pepper the docs) and the histogram sub-series the
# exposition format derives from a documented base name.  Keep this
# list short and literal — every entry is a hole in the check.
IGNORE = {
    "bps_top", "bps_doctor", "bps_fleet",
}
DERIVED_SUFFIXES = ("_bucket", "_sum", "_count")

CODE_DIRS = ("byteps_tpu", "tools")
CODE_EXTS = (".py",)
DOC_FILE = os.path.join("docs", "monitoring.md")


def scan_code(root: str) -> Tuple[Dict[str, List[str]], Set[str]]:
    """({metric_name: [files registering it]}, {collector family
    prefixes like "bps_codec_"}) across the sources."""
    out: Dict[str, List[str]] = {}
    families: Set[str] = set()
    for d in CODE_DIRS:
        for dirpath, _dirnames, filenames in os.walk(os.path.join(root,
                                                                  d)):
            for fn in filenames:
                if not fn.endswith(CODE_EXTS):
                    continue
                p = os.path.join(dirpath, fn)
                try:
                    with open(p, errors="replace") as f:
                        text = f.read()
                except OSError:
                    continue
                for name in set(REG_RE.findall(text)):
                    if name in IGNORE:
                        continue
                    out.setdefault(name, []).append(
                        os.path.relpath(p, root))
                for cname in set(COLLECTOR_RE.findall(text)):
                    families.add(f"bps_{cname}_")
    return out, families


def scan_docs(root: str) -> Tuple[Set[str], Set[str]]:
    """(exact names, wildcard prefixes) mentioned in docs/monitoring.md."""
    try:
        with open(os.path.join(root, DOC_FILE), errors="replace") as f:
            text = f.read()
    except OSError:
        return set(), set()
    exact: Set[str] = set()
    prefixes: Set[str] = set()
    for m in DOC_RE.findall(text):
        if m.endswith("*"):
            prefixes.add(m[:-1])
        elif m not in IGNORE and not m.endswith(DERIVED_SUFFIXES):
            exact.add(m)
    return exact, prefixes


def check(root: str) -> List[str]:
    """Drift report lines; empty = in sync."""
    code, families = scan_code(root)
    exact, prefixes = scan_docs(root)

    def covered(name: str) -> bool:
        return name in exact or any(name.startswith(p) for p in prefixes)

    def exported(name: str) -> bool:
        return name in code or any(name.startswith(p) for p in families)

    problems = []
    for name in sorted(n for n in code if not covered(n)):
        problems.append(
            f"UNDOCUMENTED: {name} is registered in "
            f"{', '.join(sorted(code[name])[:3])} but has no row in "
            f"{DOC_FILE}")
    for fam in sorted(families):
        if not (fam + "*" in {p + "*" for p in prefixes}
                or any(n.startswith(fam) for n in exact)):
            problems.append(
                f"UNDOCUMENTED: the {fam}* collector family is exported "
                f"but {DOC_FILE} mentions neither the family nor any "
                f"series under it")
    for name in sorted(n for n in exact if not exported(n)):
        problems.append(
            f"STALE DOC: {name} appears in {DOC_FILE} but nothing under "
            f"{'/'.join(CODE_DIRS)} registers it")
    for prefix in sorted(prefixes):
        if not (any(n.startswith(prefix) for n in code)
                or any(f.startswith(prefix) or prefix.startswith(f)
                       for f in families)):
            problems.append(
                f"STALE DOC: the {prefix}* family appears in {DOC_FILE} "
                f"but nothing under {'/'.join(CODE_DIRS)} registers a "
                f"metric with that prefix")
    return problems


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    root = args[0] if args else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    problems = check(root)
    if problems:
        print("\n".join(problems))
        print(f"\n{len(problems)} metric-doc drift problem(s); every "
              f"exported bps_* metric must appear in {DOC_FILE} (and "
              f"vice versa)")
        return 1
    print("metric docs in sync")
    return 0


if __name__ == "__main__":
    sys.exit(main())
