#!/usr/bin/env python
"""postmortem — render byteps_tpu flight-recorder bundles into one
merged, clock-aligned timeline and name the first divergent event.

When anything dies with ``BYTEPS_TPU_POSTMORTEM_DIR`` set, each worker
drops a self-contained JSON bundle (common/flightrec.py: flight-ring
events + final metrics snapshot + config + membership/ring/transport/
audit state).  This tool merges bundles from any number of workers:

    python tools/postmortem.py /path/to/postmortem-dir
    python tools/postmortem.py bundle1.json bundle2.json --json

It prints, in order:
  - a per-bundle header (rank, host, dump reason, event counts),
  - the merged cross-worker timeline, aligned on the wall clock each
    event was stamped with (bundles also carry a wall/monotonic anchor
    pair; wall-clock skew between hosts bounds the alignment error, and
    the tool warns when two bundles' anchors disagree suspiciously),
  - a cross-worker audit comparison: any (key, round) whose pulled
    digest differs between workers' audit windows — the silent
    divergence signature,
  - the FIRST BAD EVENT verdict: the earliest value-domain divergence
    (audit mismatch / lost round / non-finite gradient), else the
    earliest fatal transition (stall, dead server, eviction), else a
    clean bill.

``--json`` emits the same analysis machine-readable (one object), for
scripting and the test suite.  No dependencies beyond the stdlib.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from typing import List, Optional

BUNDLE_SCHEMA = "bps-postmortem-v1"

# Event kinds by severity class.  DIVERGENT = the values went wrong
# (what the auditor/health monitor exist to catch); FATAL = a component
# died or wedged; NOTABLE = transitions worth an eye on the timeline.
DIVERGENT_KINDS = ("audit_mismatch", "audit_lost_round", "nonfinite",
                   "audit_cross_check")
FATAL_KINDS = ("stall", "server_dead", "conn_gave_up", "evicted",
               "barrier_timeout")
NOTABLE_KINDS = ("conn_drop", "reconnected", "ring_epoch",
                 "membership_epoch", "init", "shutdown", "exit",
                 "doctor_finding")


def load_bundles(paths: List[str]) -> List[dict]:
    """Bundles from explicit files and/or directories (globbed for
    ``bps-postmortem-*.json``).  Unparseable or foreign JSON is skipped
    with a warning — one corrupt file must not hide the others."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(
                os.path.join(p, "bps-postmortem-*.json"))))
        else:
            files.append(p)
    bundles = []
    for f in files:
        try:
            with open(f) as fh:
                doc = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"postmortem: skipping {f}: {e}", file=sys.stderr)
            continue
        if doc.get("schema") != BUNDLE_SCHEMA:
            print(f"postmortem: skipping {f}: not a {BUNDLE_SCHEMA} "
                  f"bundle", file=sys.stderr)
            continue
        doc["_path"] = f
        bundles.append(doc)
    return bundles


def merged_timeline(bundles: List[dict]) -> List[dict]:
    """Every bundle's events, rank-tagged and sorted by the wall clock
    they were stamped with.  Bundles from one host share a clock
    exactly; across hosts the alignment error is the hosts' wall-clock
    skew (NTP-grade in any real deployment — and the per-bundle anchor
    pair lets a reader bound it)."""
    events = []
    for b in bundles:
        rank = b.get("rank", "?")
        for ev in b.get("events", ()):
            e = dict(ev)
            e["_rank"] = rank
            events.append(e)
    events.sort(key=lambda e: e.get("t", 0.0))
    return events


def cross_audit(bundles: List[dict]) -> List[dict]:
    """(key, round) rows whose pulled digest DIFFERS between workers'
    audit windows — each row names the key, the round, and every
    worker's digest, i.e. exactly which round diverged and who saw
    what."""
    # (key, round) -> {rank: digest}
    seen: dict = {}
    for b in bundles:
        rank = b.get("rank", "?")
        win = (b.get("extra") or {}).get("audit_window") or {}
        for key, rows in win.items():
            for row in rows:
                rnd, digest = int(row[0]), int(row[1])
                seen.setdefault((int(key), rnd), {})[rank] = digest
    out = []
    for (key, rnd), per_rank in sorted(seen.items()):
        if len(set(per_rank.values())) > 1:
            out.append({"key": key, "round": rnd,
                        "digests": {str(r): d
                                    for r, d in sorted(per_rank.items())}})
    return out


def first_bad_event(events: List[dict]) -> Optional[dict]:
    """The earliest value-domain divergence, else the earliest fatal
    transition, else None."""
    for kinds in (DIVERGENT_KINDS, FATAL_KINDS):
        for ev in events:
            if ev.get("kind") in kinds:
                return ev
    return None


def last_rounds(events: List[dict]) -> dict:
    """Per worker, per key: the last completed round recorded — where
    each worker's trajectory stopped (a worker whose last round trails
    the others marks the loss boundary)."""
    out: dict = {}
    for ev in events:
        if ev.get("kind") == "round":
            out.setdefault(str(ev["_rank"]), {})[str(ev.get("key"))] = \
                int(ev.get("round", 0))
    return out


def _fmt_ts(t: float) -> str:
    return time.strftime("%H:%M:%S", time.localtime(t)) + \
        f".{int((t % 1) * 1000):03d}"


def _fmt_event(ev: dict) -> str:
    skip = {"t", "mono", "kind", "_rank"}
    fields = " ".join(f"{k}={ev[k]}" for k in ev if k not in skip)
    return (f"{_fmt_ts(ev.get('t', 0.0))}  r{ev['_rank']:<3} "
            f"{ev.get('kind', '?'):<18} {fields}")


def diagnosis_rows(bundles: List[dict]) -> List[dict]:
    """Doctor findings open at each bundle's dump time (the ``diagnosis``
    extra section a signal-plane-armed run records) — the run's own
    verdict, rendered alongside the event timeline."""
    rows = []
    for b in bundles:
        diag = (b.get("extra") or {}).get("diagnosis") or {}
        for f in diag.get("open", []):
            rows.append({"rank": b.get("rank", "?"),
                         "rule": f.get("rule", "?"),
                         "severity": f.get("severity", "?"),
                         "subject": f.get("subject", ""),
                         "summary": f.get("summary", ""),
                         "playbook": f.get("playbook", "")})
    return rows


def device_rows(bundles: List[dict]) -> List[dict]:
    """Per-bundle device-plane verdict (the ``device`` extra section a
    devprof-armed run records): which platform each worker actually ran
    on, whether the sentinel convicted a fallback/wedge, and the last
    window's MFU — so "was it on-chip?" is answerable from the bundle
    alone, with no live cluster."""
    rows = []
    for b in bundles:
        dev = (b.get("extra") or {}).get("device") or {}
        if not dev:
            continue
        probe = dev.get("probe") or {}
        win = dev.get("last_window") or {}
        rows.append({"rank": b.get("rank", "?"),
                     "platform": probe.get("platform"),
                     "intended": probe.get("intended") or "",
                     "fallback": bool(probe.get("fallback")),
                     "reason": probe.get("reason", ""),
                     "mfu": win.get("mfu"),
                     "device_step_ms": win.get("device_step_ms"),
                     "steps_total": dev.get("steps_total", 0)})
    return rows


def fleet_section(bundles: List[dict]) -> Optional[dict]:
    """The fleet plane's offline verdict: merge every bundle's
    ``fleet.published`` ring (each worker's exact CMD_WINDOW docs) back
    into the view CMD_FLEET served and replay the fleet rule set over
    it — the same evaluation ``bps_doctor --fleet`` runs, so the two
    tools agree by construction.  None when no bundle carries a fleet
    section (BYTEPS_TPU_FLEET unset) or the package is unimportable
    (the rest of this tool stays stdlib-only)."""
    if not any((b.get("extra") or {}).get("fleet") for b in bundles):
        return None
    try:
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        if repo not in sys.path:
            sys.path.insert(0, repo)
        from byteps_tpu.common import doctor, goodput
    except ImportError as e:
        print(f"postmortem: fleet section skipped (cannot import "
              f"byteps_tpu: {e})", file=sys.stderr)
        return None
    view = doctor.fleet_view_from_bundles(bundles)
    fw = doctor.fleet_windows_from_view(view)
    if not fw:
        return None
    diag = doctor.evaluate_fleet_stream(fw)
    out = {"workers": sorted(view.get("workers") or ()),
           "windows": [w["window"] for w in fw],
           "diagnosis": diag}
    try:
        out["goodput"] = goodput.fleet_ledger(fw[-1])
    except Exception as e:
        print(f"postmortem: fleet goodput skipped: {e}",
              file=sys.stderr)
    return out


def analyze(bundles: List[dict]) -> dict:
    events = merged_timeline(bundles)
    return {
        "bundles": [{"path": b["_path"], "rank": b.get("rank"),
                     "host": b.get("host"), "reason": b.get("reason"),
                     "events": len(b.get("events", ())),
                     "events_dropped": b.get("events_dropped", 0)}
                    for b in bundles],
        "events": events,
        "cross_audit": cross_audit(bundles),
        "first_bad": first_bad_event(events),
        "last_rounds": last_rounds(events),
        "diagnosis": diagnosis_rows(bundles),
        "device": device_rows(bundles),
        "fleet": fleet_section(bundles),
    }


def render(analysis: dict, max_events: int = 200) -> str:
    lines = []
    bl = analysis["bundles"]
    ranks = sorted({b["rank"] for b in bl})
    lines.append(f"postmortem: {len(bl)} bundle(s) from "
                 f"{len(ranks)} worker(s)")
    for b in bl:
        lines.append(f"  r{b['rank']}  host={b['host']}  "
                     f"reason={b['reason']}  events={b['events']}"
                     + (f" ({b['events_dropped']} dropped)"
                        if b.get("events_dropped") else ""))
    lines.append("")
    events = analysis["events"]
    shown = events[-max_events:]
    lines.append(f"merged timeline (wall clock"
                 + (f"; last {len(shown)} of {len(events)} events"
                    if len(shown) < len(events) else "") + "):")
    for ev in shown:
        lines.append("  " + _fmt_event(ev))
    lines.append("")
    lr = analysis["last_rounds"]
    if lr:
        lines.append("last completed round per worker:")
        keys = sorted({k for rounds in lr.values() for k in rounds})
        for key in keys:
            per = {r: rounds.get(key) for r, rounds in sorted(lr.items())}
            spread = {v for v in per.values() if v is not None}
            tag = "  <-- workers disagree" if len(spread) > 1 else ""
            lines.append(
                f"  {key}: " + "  ".join(
                    f"r{r}={v if v is not None else '-'}"
                    for r, v in per.items()) + tag)
        lines.append("")
    ca = analysis["cross_audit"]
    if ca:
        lines.append("cross-worker audit: DIVERGENT (key, round) pulls:")
        for row in ca:
            digs = "  ".join(f"r{r}={d:08x}"
                             for r, d in row["digests"].items())
            lines.append(f"  key {row['key']} round {row['round']}: "
                         f"{digs}")
        lines.append("")
    elif len(ranks) > 1:
        lines.append("cross-worker audit: no divergent (key, round) "
                     "digests across bundles")
        lines.append("")
    diag = analysis.get("diagnosis") or []
    if diag:
        lines.append("doctor findings open at dump time "
                     "(replay the full rule set with: "
                     "python tools/bps_doctor.py <bundles>):")
        for row in diag:
            lines.append(f"  r{row['rank']}  [{row['severity']}] "
                         f"{row['rule']} ({row['subject']})  "
                         f"-> {row['playbook']}")
        lines.append("")
    dv = analysis.get("device") or []
    if dv:
        lines.append("device plane (was it on-chip?):")
        for row in dv:
            mfu = (f"{row['mfu']:.3f}"
                   if isinstance(row.get("mfu"), (int, float)) else "-")
            ms = (f"{row['device_step_ms']:.2f}ms"
                  if isinstance(row.get("device_step_ms"), (int, float))
                  else "-")
            want = (f" (intended {row['intended']})"
                    if row["intended"] else "")
            tag = (f"  <-- FALLBACK: {row['reason']}"
                   if row["fallback"] else "")
            lines.append(
                f"  r{row['rank']}  platform={row['platform']}{want}  "
                f"mfu={mfu}  device_step={ms}  "
                f"steps={row['steps_total']}{tag}")
        lines.append("")
    fs = analysis.get("fleet")
    if fs:
        d = fs["diagnosis"]
        lines.append(f"fleet ({len(fs['workers'])} worker ring(s), "
                     f"{len(fs['windows'])} aligned window(s) replayed):")
        if d.get("healthy"):
            lines.append("  healthy — no open fleet findings")
        for f in d.get("open", []):
            lines.append(f"  [{f['severity']}] {f['rule']} "
                         f"({f['subject']})  -> {f['playbook']}")
        gp = fs.get("goodput")
        if gp:
            lines.append(
                f"  goodput {gp.get('goodput_pct', 0.0):.1f}% compute "
                f"over {gp.get('total_s', 0.0):.1f}s fleet wall-time "
                f"(last window)")
        lines.append("")
    fb = analysis["first_bad"]
    if fb is not None:
        cls = ("value-domain divergence"
               if fb.get("kind") in DIVERGENT_KINDS else "fatal transition")
        lines.append(f"FIRST BAD EVENT ({cls}):")
        lines.append("  " + _fmt_event(fb))
    else:
        lines.append("FIRST BAD EVENT: none recorded — no divergence or "
                     "fatal transition in any bundle's window")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="+",
                    help="bundle files and/or directories to merge")
    ap.add_argument("--json", action="store_true",
                    help="emit the analysis as one JSON object")
    ap.add_argument("--max-events", type=int, default=200,
                    help="timeline lines to print (default 200)")
    args = ap.parse_args(argv)
    bundles = load_bundles(args.paths)
    if not bundles:
        print("postmortem: no bundles found (is "
              "BYTEPS_TPU_POSTMORTEM_DIR set on the workers?)",
              file=sys.stderr)
        return 1
    analysis = analyze(bundles)
    if args.json:
        print(json.dumps(analysis))
    else:
        print(render(analysis, max_events=args.max_events))
    return 0


if __name__ == "__main__":
    sys.exit(main())
