#!/usr/bin/env python
"""bps_top — live terminal dashboard for the byteps_tpu metrics endpoint.

Polls the Prometheus text endpoint a worker serves when launched with
``BYTEPS_TPU_METRICS_PORT`` (see docs/monitoring.md) and renders the
interesting slices: push-pull throughput, push RTT / dispatcher-queue
latency percentiles, codec latency, the step critical-path breakdown
from the last analyzed trace window (``bps_step_critical_path_*``, see
docs/timeline.md), the gradient-health / audit panel (``bps_grad_*`` and
``bps_audit_*``, see docs/monitoring.md "Auditing & postmortem"),
per-worker round lag (straggler view), the codec/transport/fusion
counter panels, and — when the signal plane is armed
(``BYTEPS_TPU_SIGNAL_WINDOW_S`` > 0) — the doctor panel: the open
findings from the ``/diagnosis`` route, severity-ranked, each with its
playbook anchor (see docs/monitoring.md "Doctor").

Usage:
    python tools/bps_top.py --url http://host:9100/metrics
    python tools/bps_top.py --port 9100                  # localhost
    python tools/bps_top.py --port 9100 --plain          # no curses
    python tools/bps_top.py --port 9100 --once           # one snapshot

Curses is used when stdout is a tty (fall back with --plain); --once
prints a single snapshot and exits (handy over ssh or in a pipeline).
No dependencies beyond the stdlib — the parser speaks just enough of
the exposition format for our own endpoint.
"""

from __future__ import annotations

import argparse
import re
import sys
import time
import urllib.request

_LINE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$")
_LABEL = re.compile(r'(\w+)="([^"]*)"')


def fetch(url: str, timeout: float = 3.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def fetch_diagnosis(metrics_url: str, timeout: float = 3.0):
    """The doctor's /diagnosis JSON from the same endpoint, or None when
    the signal plane is off (404) / unreachable — the panel then simply
    doesn't render."""
    import json
    base = metrics_url.rsplit("/metrics", 1)[0]
    try:
        return json.loads(fetch(base + "/diagnosis", timeout=timeout))
    except Exception:
        return None


def parse(text: str) -> dict:
    """{name: {frozenset(label items) or (): float}} — enough structure
    for gauges/counters and histogram _bucket/_sum/_count series."""
    out: dict = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _LINE.match(line.strip())
        if not m:
            continue
        name, labels, value = m.groups()
        key = tuple(sorted(_LABEL.findall(labels))) if labels else ()
        try:
            out.setdefault(name, {})[key] = float(value)
        except ValueError:
            continue
    return out


def _get(metrics: dict, name: str, default: float = 0.0) -> float:
    series = metrics.get(name)
    if not series:
        return default
    return sum(series.values())


def quantile(metrics: dict, hist: str, q: float) -> float:
    """Linear-interpolated quantile from cumulative _bucket series."""
    series = metrics.get(hist + "_bucket") or {}
    buckets = []
    for key, cum in series.items():
        le = dict(key).get("le")
        if le is None:
            continue
        buckets.append((float("inf") if le == "+Inf" else float(le), cum))
    if not buckets:
        return 0.0
    buckets.sort()
    total = buckets[-1][1]
    if total <= 0:
        return 0.0
    target = q * total
    prev_le, prev_cum = 0.0, 0.0
    for le, cum in buckets:
        if cum >= target:
            if le == float("inf"):
                return prev_le
            span = cum - prev_cum
            frac = (target - prev_cum) / span if span else 1.0
            return prev_le + (le - prev_le) * frac
        prev_le, prev_cum = le, cum
    return buckets[-1][0]


def _fmt_s(v: float) -> str:
    if v >= 1.0:
        return f"{v:6.2f}s"
    if v >= 1e-3:
        return f"{v * 1e3:6.2f}ms"
    return f"{v * 1e6:6.0f}us"


def _fmt_bytes(v: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if v < 1024 or unit == "TB":
            return f"{v:8.1f}{unit}"
        v /= 1024
    return f"{v:8.1f}TB"


def render(metrics: dict, prev: dict, dt: float,
           diagnosis: dict = None) -> list:
    """Dashboard lines from the current (and previous, for rates) poll."""
    lines = []
    now = time.strftime("%H:%M:%S")
    pushed = _get(metrics, "bps_pushpull_bytes_total")
    rate = ((pushed - _get(prev, "bps_pushpull_bytes_total")) / dt
            if prev and dt > 0 else 0.0)
    lines.append(f"bps_top  {now}   push_pull {_fmt_bytes(pushed)} total"
                 f"   {_fmt_bytes(rate)}/s")
    lines.append("")

    # Doctor panel (/diagnosis route; BYTEPS_TPU_SIGNAL_WINDOW_S > 0).
    # Open findings first — a diagnosed bottleneck or failure should be
    # the first thing on screen after the throughput line.
    if diagnosis is not None and diagnosis.get("armed", True):
        open_f = diagnosis.get("open") or []
        if open_f:
            lines.append(f"doctor: {len(open_f)} open finding(s)   "
                         f"[window {diagnosis.get('window', '?')}]")
            for f in open_f[:8]:
                lines.append(
                    f"  [{f.get('severity', '?'):<8}] "
                    f"{f.get('rule', '?')} ({f.get('subject', '')})  "
                    f"-> {f.get('playbook', '')}")
                summary = f.get("summary", "")
                if summary:
                    lines.append(f"      {summary[:100]}")
        else:
            lines.append(f"doctor: healthy   "
                         f"[window {diagnosis.get('window', '?')}, "
                         f"{diagnosis.get('findings_total', 0)} cleared]")
        lines.append("")

    # Fleet panel (BYTEPS_TPU_FLEET=1): the goodput ledger's exact
    # wall-time partition — compute share first, then every category as
    # a bar (they sum to 100 by construction) — plus the CMD_WINDOW
    # plumbing counters.  Absent in unarmed runs: the gauges are only
    # registered when the fleet plane publishes (the
    # quiet-when-unarmed law).
    gp = metrics.get("bps_fleet_goodput_pct")
    if gp is not None:
        cats = {dict(k).get("category", "?"): v for k, v in
                (metrics.get("bps_fleet_time_pct") or {}).items()}
        pub = int(_get(metrics, "bps_fleet_publishes_total"))
        held = int(_get(metrics, "bps_fleet_windows_held"))
        lines.append(f"fleet: goodput {_get(metrics, 'bps_fleet_goodput_pct'):5.1f}%"
                     f"   [{pub} window(s) published, {held} held "
                     f"server-side]")
        for cat, v in sorted(cats.items(), key=lambda kv: -kv[1]):
            bar = "#" * int(30 * v / 100.0)
            lines.append(f"  {cat:<16}{v:5.1f}%  {bar}")
        lines.append("")

    # Device panel (BYTEPS_TPU_DEVPROF=1): per-worker MFU, mean device
    # step time, the platform the sentinel actually probed, and the
    # fallback conviction flag — "is it on-chip, and how hot?" at a
    # glance.  Absent in unarmed runs: devprof registers its gauges only
    # when armed (the quiet-when-unarmed law).
    mfu = {dict(k).get("worker", "?"): v for k, v in
           (metrics.get("bps_mfu") or {}).items()}
    step_ms = {dict(k).get("worker", "?"): v for k, v in
               (metrics.get("bps_device_step_ms") or {}).items()}
    fb = {dict(k).get("worker", "?"): (dict(k).get("platform", "?"), v)
          for k, v in (metrics.get("bps_device_fallback") or {}).items()}
    if mfu or step_ms or fb:
        lines.append("device (MFU / step time / platform per worker)")
        for wid in sorted(set(mfu) | set(step_ms) | set(fb)):
            plat, fell = fb.get(wid, ("?", 0.0))
            m = mfu.get(wid)
            mtxt = f"mfu {m:6.3f}" if m is not None else "mfu      -"
            bar = "#" * int(30 * m) if m else ""
            ms = step_ms.get(wid)
            mstxt = (f"step {ms:8.2f}ms" if ms is not None
                     else "step        -")
            flag = "  <-- DEVICE FALLBACK" if fell else ""
            lines.append(f"  worker {wid:>3}  {mtxt}  {mstxt}  "
                         f"platform {plat:<8} {bar}{flag}")
        lines.append("")

    # Tuner panel (BYTEPS_TPU_TUNER=1): the current wire codec per key
    # (bps_codec_active gauge — set at every renegotiation apply) with
    # per-key switch counts, hottest-switching first.  Absent when no
    # key ever renegotiated.
    active = metrics.get("bps_codec_active") or {}
    if active:
        switches = {dict(k).get("key"): int(v) for k, v in
                    (metrics.get("bps_tuner_key_switches_total")
                     or {}).items()}
        total_sw = int(_get(metrics, "bps_tuner_switches_total"))
        lines.append(f"tuner: {len(active)} renegotiated key(s), "
                     f"{total_sw} switch(es) total")
        names = {0: "raw", 1: "onebit", 2: "topk", 3: "randomk",
                 4: "dither", 5: "qblock"}
        ranked = sorted(active.items(),
                        key=lambda kv: -switches.get(
                            dict(kv[0]).get("key"), 0))
        for key, v in ranked[:12]:
            name = dict(key).get("key", "?")
            lines.append(
                f"  {name[:28]:<28} codec {names.get(int(v), '?'):<8}"
                f" switches {switches.get(name, 0):3d}")
        lines.append("")

    # Knob-plane panel (CMD_KNOB): the live epoch and per-knob values
    # the fleet is actually running under, plus the switch count (which
    # feeds the doctor's knob_thrash rule).  Absent until a knob set
    # lands — unarmed runs keep the gauges unregistered.
    epoch = _get(metrics, "bps_knob_epoch")
    if epoch:
        sw = int(_get(metrics, "bps_knob_switches_total"))
        sw_rate = ((sw - _get(prev, "bps_knob_switches_total")) / dt
                   if prev and dt > 0 else 0.0)
        vals = {dict(k).get("knob"): v for k, v in
                (metrics.get("bps_knob_value") or {}).items()}
        kv = "  ".join(f"{k}={int(v)}" for k, v in sorted(vals.items()))
        flag = "  <-- thrashing?" if sw_rate > 0.5 else ""
        lines.append(f"knob plane: epoch {int(epoch)}   {kv}   "
                     f"switches {sw}{flag}")
        lines.append("")

    # Hierarchical-reduction panel (BYTEPS_TPU_HIERARCHY=1): this
    # worker's slice role and the wire bytes its followers never sent.
    # Absent in flat runs — the gauges are only registered by an armed
    # reducer.
    ss = metrics.get("bps_hierarchy_slice_size")
    if ss is not None:
        saved = _get(metrics, "bps_hierarchy_wire_bytes_saved_total")
        saved_rate = ((saved - _get(prev,
                                    "bps_hierarchy_wire_bytes_saved_total"))
                      / dt if prev and dt > 0 else 0.0)
        role = ("leader" if _get(metrics, "bps_hierarchy_is_leader")
                else "follower")
        lines.append(
            f"hierarchy: slice {int(_get(metrics, 'bps_hierarchy_slice_id'))}"
            f" ({int(_get(metrics, 'bps_hierarchy_slice_members'))} chips,"
            f" slice_size {int(_get(metrics, 'bps_hierarchy_slice_size'))})"
            f"   role {role}   wire saved {_fmt_bytes(saved)}"
            f"   {_fmt_bytes(saved_rate)}/s")
        lines.append("")

    lines.append("latency                 p50      p95      count")
    for label, hist in (("push RTT", "bps_push_rtt_seconds"),
                        ("queue wait", "bps_dispatch_queue_wait_seconds"),
                        ("codec encode", "bps_codec_encode_seconds"),
                        ("codec decode", "bps_codec_decode_seconds"),
                        ("step time", "bps_step_time_seconds")):
        count = _get(metrics, hist + "_count")
        if count <= 0:
            continue
        lines.append(f"  {label:<18}{_fmt_s(quantile(metrics, hist, 0.5))}"
                     f"  {_fmt_s(quantile(metrics, hist, 0.95))}"
                     f"  {int(count):9d}")
    depth = _get(metrics, "bps_dispatch_queue_depth")
    lines.append(f"  dispatcher queue depth: {int(depth)}")
    lines.append("")

    cp = metrics.get("bps_step_critical_path_seconds") or {}
    if cp:
        lines.append("step critical path (per-step mean, last trace window)")
        total = sum(cp.values()) or 1.0
        for key, v in sorted(cp.items(), key=lambda kv: -kv[1]):
            comp = dict(key).get("component", "?")
            bar = "#" * int(30 * v / total)
            lines.append(f"  {comp:<12}{_fmt_s(v)}  {bar}")
        sw = metrics.get("bps_step_straggler_wait_seconds") or {}
        for key, v in sorted(sw.items(), key=lambda kv: -kv[1]):
            if v > 0:
                wid = dict(key).get("worker", "?")
                lines.append(f"  peers waited {_fmt_s(v)} on worker {wid}")
        lines.append("")

    # Gradient-health panel (BYTEPS_TPU_HEALTH_SAMPLE_ROUNDS > 0 /
    # BYTEPS_TPU_AUDIT=1): per-key value stats, non-finite keys first —
    # a NaN storm or audit mismatch must be the first thing on screen.
    norms = metrics.get("bps_grad_norm") or {}
    if norms or _get(metrics, "bps_audit_checked_total"):
        checked = int(_get(metrics, "bps_audit_checked_total"))
        mism = int(_get(metrics, "bps_audit_mismatch_total"))
        skew = int(_get(metrics, "bps_audit_round_skew_total"))
        bad = int(_get(metrics, "bps_grad_nonfinite_total"))
        head = "gradient health"
        if checked:
            head += (f"   [audit: {checked} checked, {mism} mismatch, "
                     f"{skew} lost-round]")
        if mism or skew:
            head += "  <-- AUDIT FAILURE"
        lines.append(head)
        absmax = {dict(k).get("key"): v for k, v in
                  (metrics.get("bps_grad_absmax") or {}).items()}
        nonfin = {dict(k).get("key"): v for k, v in
                  (metrics.get("bps_grad_nonfinite") or {}).items()}
        efres = {dict(k).get("key"): v for k, v in
                 (metrics.get("bps_grad_ef_residual_norm") or {}).items()}
        ranked = sorted(norms.items(),
                        key=lambda kv: (-nonfin.get(
                            dict(kv[0]).get("key"), 0), -kv[1]))
        for key, v in ranked[:12]:
            name = dict(key).get("key", "?")
            nf = int(nonfin.get(name, 0))
            ef = efres.get(name)
            eftxt = f"  ef {ef:10.3g}" if ef else ""
            flag = f"  <-- {nf} NaN/Inf" if nf else ""
            lines.append(f"  {name[:28]:<28} norm {v:10.3g}  max "
                         f"{absmax.get(name, 0.0):10.3g}{eftxt}{flag}")
        if bad:
            lines.append(f"  non-finite samples total: {bad}")
        lines.append("")

    srv_alive = metrics.get("bps_server_alive") or {}
    if srv_alive:
        ring_epoch = int(_get(metrics, "bps_ring_epoch"))
        owned = {dict(k).get("server"): v
                 for k, v in (metrics.get("bps_keys_owned") or {}).items()}
        mig = {}
        for k, v in (metrics.get("bps_server_migrations") or {}).items():
            d = dict(k)
            mig.setdefault(d.get("server"), {})[d.get("direction")] = int(v)
        slot_bytes = {dict(k).get("server"): int(v) for k, v in
                      (metrics.get("bps_opt_slot_bytes") or {}).items()}
        repl_lag = {dict(k).get("server"): int(v) for k, v in
                    (metrics.get("bps_repl_lag_rounds") or {}).items()}
        total_owned = sum(owned.values()) or 1
        lines.append(f"PS servers (ring epoch {ring_epoch})")
        for key, alive in sorted(srv_alive.items(),
                                 key=lambda kv: dict(kv[0]).get("server",
                                                                "")):
            sid = dict(key).get("server", "?")
            n = int(owned.get(sid, 0))
            bar = "#" * int(30 * n / total_owned)
            m = mig.get(sid, {})
            migtxt = (f"  mig in/out {m.get('in', 0)}/{m.get('out', 0)}"
                      if m.get("in") or m.get("out") else "")
            flag = "" if alive else "  <-- dead/retired"
            ob = slot_bytes.get(sid)
            opttxt = f"  opt slots {_fmt_bytes(ob)}" if ob else ""
            # Chain replication (BYTEPS_TPU_REPL=1): rounds the ring
            # successor has not acked yet — non-zero is a growing
            # would-be loss window (doctor rule replication_lag).
            rl = repl_lag.get(sid)
            repltxt = (f"  repl lag {rl}" if rl else "")
            lines.append(f"  server {sid:>3}  keys {n:5d}  {bar}"
                         f"{migtxt}{opttxt}{repltxt}{flag}")
        repl_bytes = _get(metrics, "bps_repl_bytes_total")
        if repl_bytes:
            lines.append(f"  replication: {_fmt_bytes(repl_bytes)} "
                         f"shipped to ring successors")
        # Autoscaler actions (BYTEPS_TPU_AUTOSCALE=1): executed ring
        # transitions by direction.
        asc = {dict(k).get("dir"): int(v) for k, v in
               (metrics.get("bps_autoscale_actions_total") or {}).items()}
        if asc:
            lines.append(f"  autoscale: up {asc.get('up', 0)} / "
                         f"down {asc.get('down', 0)} action(s)")
        lines.append("")

    # Server-resident optimizer plane: per-key published update counts
    # (bps_param_version advances exactly one per completed round — a
    # frozen row under advancing rounds is the param_version_stall
    # doctor rule in the making).
    pv = metrics.get("bps_param_version") or {}
    if pv:
        lines.append("server-resident optimizer (param_version per key)")
        for key, v in sorted(pv.items(),
                             key=lambda kv: dict(kv[0]).get("key", "")):
            name = dict(key).get("key", "?")
            lines.append(f"  key {name:<24} updates {int(v):8d}")
        lines.append("")

    # Row-sparse embedding plane (docs/sparse-embedding.md): rows the PS
    # tier served, resident table bytes per server, and the worker-side
    # hot-row cache hit rate over the last interval — a collapsing rate
    # under growing pull bytes is the embedding_cache_thrash doctor
    # rule in the making.
    rows_served = _get(metrics, "bps_embed_rows_served_total")
    tbl = {dict(k).get("server", "?"): int(v) for k, v in
           (metrics.get("bps_embed_table_bytes") or {}).items()}
    hits = _get(metrics, "bps_embed_cache_hits")
    misses = _get(metrics, "bps_embed_cache_misses")
    if rows_served or tbl or hits or misses:
        lines.append("embedding (row-sparse lookup tier)")
        lines.append(f"  rows served {int(rows_served):>12d}   table "
                     f"{_fmt_bytes(sum(tbl.values()))} resident")
        for sid in sorted(tbl):
            lines.append(f"    server {sid:>3}  {_fmt_bytes(tbl[sid])}")
        dh = hits - _get(prev, "bps_embed_cache_hits")
        dm = misses - _get(prev, "bps_embed_cache_misses")
        if dh + dm > 0:
            rate = dh / (dh + dm)
            bar = "#" * int(30 * rate)
            lines.append(f"  cache hit rate {rate:7.1%}  {bar}")
        pb = (_get(metrics, "bps_embed_pull_bytes_total")
              - _get(prev, "bps_embed_pull_bytes_total"))
        if pb > 0 and dt > 0:
            lines.append(f"  pull wire {_fmt_bytes(pb / dt)}/s")
        lines.append("")

    lag = metrics.get("bps_worker_round_lag") or {}
    if lag:
        epoch = int(_get(metrics, "bps_membership_epoch"))
        n_alive = int(_get(metrics, "bps_workers_alive"))
        header = "workers (round lag — stragglers first)"
        if epoch > 0:
            header += f"   [membership epoch {epoch}, {n_alive} alive]"
        lines.append(header)
        # A lagging worker that is no longer a member is not slow — it is
        # GONE (left/evicted); its rounds re-finalized and nothing waits
        # on it.  Only a lagging LIVE worker deserves the straggler flag.
        alive = {dict(k).get("worker"): v
                 for k, v in (metrics.get("bps_worker_alive") or {}).items()}
        ranked = sorted(lag.items(), key=lambda kv: -kv[1])
        worst_live = max((v for k, v in ranked
                          if alive.get(dict(k).get("worker"), 1)),
                         default=0)
        for key, v in ranked:
            wid = dict(key).get("worker", "?")
            bar = "#" * min(40, int(v))
            if not alive.get(wid, 1):
                flag = "  <-- evicted/gone"
            elif v > 0 and v == worst_live:
                flag = "  <-- straggler"
            else:
                flag = ""
            lines.append(f"  worker {wid:>3}  lag {int(v):4d}  {bar}{flag}")
        lines.append("")

    for panel, prefix in (("transport", "bps_transport_"),
                          ("codec", "bps_codec_"),
                          ("fusion", "bps_fusion_")):
        rows = [(n[len(prefix):], _get(metrics, n))
                for n in sorted(metrics)
                if n.startswith(prefix) and not n.endswith(
                    ("_bucket", "_sum", "_count"))
                and "_seconds" not in n]
        rows = [(k, v) for k, v in rows if v]
        if rows:
            lines.append(panel)
            for k, v in rows:
                lines.append(f"  {k:<28}{int(v):>12d}")
            lines.append("")
    return lines


def run_plain(url: str, interval: float, once: bool) -> int:
    prev: dict = {}
    t_prev = time.monotonic()
    while True:
        try:
            metrics = parse(fetch(url))
        except OSError as e:
            print(f"bps_top: cannot reach {url}: {e}", file=sys.stderr)
            if once:
                return 1
            time.sleep(interval)
            continue
        now = time.monotonic()
        lines = render(metrics, prev, now - t_prev,
                       diagnosis=fetch_diagnosis(url))
        prev, t_prev = metrics, now
        if once:
            print("\n".join(lines))
            return 0
        # ANSI clear + home: a poor man's curses that survives pipes.
        sys.stdout.write("\x1b[2J\x1b[H" + "\n".join(lines) + "\n")
        sys.stdout.flush()
        time.sleep(interval)


def run_curses(url: str, interval: float) -> int:
    import curses

    def loop(scr):
        curses.curs_set(0)
        scr.nodelay(True)
        prev: dict = {}
        t_prev = time.monotonic()
        while True:
            try:
                metrics = parse(fetch(url))
                now = time.monotonic()
                lines = render(metrics, prev, now - t_prev,
                               diagnosis=fetch_diagnosis(url))
                prev, t_prev = metrics, now
            except OSError as e:
                lines = [f"bps_top: cannot reach {url}", f"  {e}",
                         "", "retrying... (q quits)"]
            scr.erase()
            h, w = scr.getmaxyx()
            for i, line in enumerate(lines[:h - 1]):
                scr.addnstr(i, 0, line, w - 1)
            scr.refresh()
            t_end = time.monotonic() + interval
            while time.monotonic() < t_end:
                if scr.getch() in (ord("q"), 27):
                    return
                time.sleep(0.05)

    curses.wrapper(loop)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", help="full metrics URL")
    ap.add_argument("--port", type=int,
                    help="shorthand for http://127.0.0.1:<port>/metrics")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="poll interval seconds (default 2)")
    ap.add_argument("--plain", action="store_true",
                    help="ANSI refresh loop instead of curses")
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit")
    args = ap.parse_args(argv)
    if not args.url and not args.port:
        ap.error("need --url or --port")
    url = args.url or f"http://127.0.0.1:{args.port}/metrics"
    if args.once or args.plain or not sys.stdout.isatty():
        return run_plain(url, args.interval, args.once)
    try:
        return run_curses(url, args.interval)
    except Exception:
        return run_plain(url, args.interval, once=False)


if __name__ == "__main__":
    sys.exit(main())
