#!/usr/bin/env python
"""bench_compare — regression gate over the BENCH_* / MULTICHIP_* record
series.

Every PR's driver run leaves ``BENCH_rNN.json`` / ``MULTICHIP_rNN.json``
records at the repo root (bench.py wrapper shape: ``{"n", "cmd", "rc",
"parsed": {"metric", "value", "unit", "detail": {...}}}``).  The r05
incident (ROADMAP "bench reality check") showed how a silent regression
rides that history: a CPU-fallback number that *reads* like an on-chip
one becomes the implicit baseline.  bench.py now refuses to *write*
such records unstamped; this tool closes the read side:

  For the LATEST record of each (headline metric, device platform)
  pair, compare against the BEST prior non-fallback record of the same
  pair and flag any regression worse than ``--threshold`` (default
  10%).

Fallback records (``"fallback": true`` stamp, ``cpu_fallback_*`` unit,
or a ``cpu-fallback`` provenance note) are never used as baselines, and
platform pairing means a fallback candidate is only ever judged against
other explicit-CPU numbers — apples to apples by construction.
Direction is inferred from the metric: ``*_ms`` / second-ish units are
lower-is-better, everything else higher-is-better.

    python tools/bench_compare.py [root] [--json] [--threshold 0.10]

Exit codes: 0 = no regression (or nothing comparable), 3 = regression
flagged (bench.py's refusal convention), 1 = usage error.  Wired as a
self-tested fast tier-1 test (tests/test_bench_compare.py) on synthetic
records, so the gate itself can't silently rot.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import List, Optional

_SEQ_RE = re.compile(r"_r(\d+)\.json$")

DEFAULT_THRESHOLD = 0.10


def _is_fallback(parsed: dict) -> bool:
    detail = parsed.get("detail") or {}
    if detail.get("fallback") or detail.get("device_fallback"):
        return True
    if str(parsed.get("unit", "")).startswith("cpu_fallback_"):
        return True
    note = str(detail.get("note", ""))
    return "cpu-fallback" in note or "cpu fallback" in note


def _platform(parsed: dict) -> str:
    detail = parsed.get("detail") or {}
    p = detail.get("device_platform")
    if p:
        return str(p)
    # Pre-stamp records: infer from the fallback note, else unknown.
    return "cpu" if _is_fallback(parsed) else "unknown"


# Throughput-ish shapes that are HIGHER-is-better and must be
# recognized explicitly: a rate metric named "*_per_s" / "*_rows_s"
# would otherwise match the "_s" time suffix below and read as
# lower-is-better — a goodput IMPROVEMENT would then flag as a
# regression.  Checked before the time-suffix rules for exactly that
# reason.
_HIGHER_METRIC_SUFFIXES = (
    "_mbps", "_gbps", "_mb_s", "_gb_s", "_goodput", "_throughput",
    "_per_s", "_per_sec", "_rows_s", "_tokens_s", "_items_s", "_qps",
    "_mfu", "_efficiency", "_pct_of_floor", "_pct_of_peak", "_saved_pct",
    "_hit_rate",
    # BENCH_FLEET's goodput-ledger headline: a percentage where more
    # compute share is better — named explicitly so it never drifts
    # onto a lower-is-better *_pct rule (the _gap_pct family below).
    "_goodput_pct",
)
_HIGHER_UNITS = {
    "mbps", "gbps", "mb/s", "gb/s", "mb_s", "gb_s", "goodput_mbps",
    "per_s", "per_sec", "qps", "rows_s", "rows_per_s", "tokens_s",
    "items_per_s", "steps_per_s", "pct_of_floor", "pct_of_peak", "mfu",
    "ratio", "x",
}

# Percentile-tail names (BENCH_SPARSE p99 pull latency and friends):
# a pNN_ prefix marks a latency-distribution tail, lower-is-better
# whatever the suffix spells — checked after the explicit-higher rules
# so a hypothetical "p99_*_hit_rate" still reads as a rate.
_PCTL_PREFIXES = ("p50_", "p90_", "p95_", "p99_", "p999_")


def _lower_is_better(metric: str, unit: str) -> bool:
    unit = unit[len("cpu_fallback_"):] if unit.startswith(
        "cpu_fallback_") else unit
    # Explicit higher-is-better first: throughput/goodput/efficiency
    # shapes, including rate names that also end in "_s".
    if metric.endswith(_HIGHER_METRIC_SUFFIXES) \
            or unit.lower() in _HIGHER_UNITS:
        return False
    if metric.startswith(_PCTL_PREFIXES):
        return True
    if metric.endswith(("_ms", "_ns", "_s", "_seconds", "_latency")):
        return True
    # The gap family (BENCH_AUTOTUNE / BENCH_SERVEROPT / BENCH_KNOB):
    # the headline is the step-time GAP between the adaptive run and
    # its hand-tuned/baseline config — a percentage where smaller means
    # more of the gap closed (0 = converged, negative = outright
    # faster).  Without this, "pct" would read as higher-is-better and
    # a converging tuner would flag as a regression.
    if metric.endswith("_gap_pct") or unit == "pct_gap":
        return True
    # The robustness families (BENCH_ELASTIC with replication armed):
    # lost rounds on a failover, how far replication trails the publish
    # cursor, and how long the autoscaler took to notice pressure — all
    # counts where 0 is the law and any growth is a regression.  A bare
    # "_rounds" suffix would otherwise fall through to higher-is-better
    # (completed_round-style progress counters legitimately read that
    # way), so the loss/lag shapes are named explicitly
    # (autoscale_detect_ms already reads lower via the _ms rule above).
    if metric.endswith(("_lost_rounds", "_lag_rounds", "_overhead_pct")):
        return True
    return unit in ("ms", "ns", "s", "seconds", "us")


def load_records(root: str) -> List[dict]:
    """Flat record list from BENCH_*.json / MULTICHIP_*.json files.
    Unparseable files are skipped with a warning — one corrupt record
    must not hide the rest of the series."""
    out: List[dict] = []
    for pattern in ("BENCH_*.json", "MULTICHIP_*.json"):
        for path in sorted(glob.glob(os.path.join(root, pattern))):
            m = _SEQ_RE.search(os.path.basename(path))
            seq = int(m.group(1)) if m else -1
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, ValueError) as e:
                print(f"bench_compare: skipping {path}: {e}",
                      file=sys.stderr)
                continue
            parsed = doc.get("parsed") if isinstance(doc, dict) else None
            if not parsed and isinstance(doc, dict) and "metric" in doc:
                parsed = doc              # raw bench.py output shape
            if parsed and "metric" in parsed and isinstance(
                    parsed.get("value"), (int, float)):
                out.append({
                    "file": os.path.basename(path),
                    "seq": seq if seq >= 0 else int(doc.get("n", -1)),
                    "metric": str(parsed["metric"]),
                    "value": float(parsed["value"]),
                    "unit": str(parsed.get("unit", "")),
                    "platform": _platform(parsed),
                    "fallback": _is_fallback(parsed),
                })
            elif isinstance(doc, dict) and "ok" in doc:
                # MULTICHIP dryrun records: {"n_devices", "rc", "ok"} —
                # gate ok=true -> false regressions (a broken multichip
                # path is a 100% regression of its one headline bit).
                out.append({
                    "file": os.path.basename(path),
                    "seq": seq,
                    "metric": "multichip_dryrun_ok",
                    "value": 1.0 if doc.get("ok") else 0.0,
                    "unit": "bool",
                    "platform": "dryrun",
                    "fallback": False,
                })
    return out


def check(records: List[dict],
          threshold: float = DEFAULT_THRESHOLD) -> dict:
    """The gate, as a pure function over record dicts (the self-test's
    entry point).  Returns {"groups": [...], "regressions": [...]}."""
    groups: dict = {}
    for r in records:
        groups.setdefault((r["metric"], r["platform"]), []).append(r)
    rows, regressions = [], []
    for (metric, platform), recs in sorted(groups.items()):
        recs = sorted(recs, key=lambda r: r["seq"])
        latest = recs[-1]
        lower = _lower_is_better(metric, latest["unit"])
        prior = [r for r in recs[:-1] if not r["fallback"]]
        row = {"metric": metric, "platform": platform,
               "latest": latest["value"], "latest_file": latest["file"],
               "latest_fallback": latest["fallback"],
               "direction": "lower" if lower else "higher",
               "records": len(recs)}
        if not prior:
            row.update(status="no-baseline", baseline=None)
            rows.append(row)
            continue
        best = (min if lower else max)(prior, key=lambda r: r["value"])
        base = best["value"]
        if base == 0:
            change = 0.0 if latest["value"] == 0 else 1.0
        elif lower:
            change = (latest["value"] - base) / abs(base)
        else:
            change = (base - latest["value"]) / abs(base)
        row.update(baseline=base, baseline_file=best["file"],
                   regression_frac=round(change, 4))
        if change > threshold:
            row["status"] = "REGRESSED"
            regressions.append(row)
        else:
            row["status"] = "ok"
        rows.append(row)
    return {"threshold": threshold, "groups": rows,
            "regressions": regressions}


def render(report: dict) -> str:
    lines = [f"bench_compare: {len(report['groups'])} (metric, "
             f"platform) group(s), threshold "
             f"{report['threshold']:.0%}"]
    for row in report["groups"]:
        if row["status"] == "no-baseline":
            detail = "no prior non-fallback baseline"
        else:
            detail = (f"latest {row['latest']:g} vs best "
                      f"{row['baseline']:g} ({row['baseline_file']}), "
                      f"{row['regression_frac']:+.1%} "
                      f"({row['direction']}-is-better)")
        tag = " <-- REGRESSED" if row["status"] == "REGRESSED" else ""
        fb = " [fallback]" if row.get("latest_fallback") else ""
        lines.append(f"  {row['metric']} @{row['platform']}{fb}: "
                     f"{detail}{tag}")
    if report["regressions"]:
        lines.append(f"{len(report['regressions'])} metric(s) regressed "
                     f"> {report['threshold']:.0%} vs the best prior "
                     f"non-fallback record")
    else:
        lines.append("no regressions")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("root", nargs="?",
                    default=os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__))),
                    help="directory holding BENCH_*.json / "
                         "MULTICHIP_*.json (default: repo root)")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="regression fraction to flag (default 0.10)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as one JSON object")
    args = ap.parse_args(argv)
    records = load_records(args.root)
    report = check(records, threshold=args.threshold)
    if args.json:
        print(json.dumps(report))
    else:
        print(render(report))
    return 3 if report["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
