#!/bin/sh
# Probe the device tunnel until it answers, then immediately run the
# on-chip MFU sweep.  Round-5 front-loading: the tunnel wedged at round
# end twice (r03, r04) taking the round's best numbers with it — so the
# moment it comes back, measure first and ask questions later.
#
# Usage: tools/tunnel_watch.sh [sweep_out.jsonl] [watch.log]
OUT=${1:-bench_runs/r05_sweep1.jsonl}
LOG=${2:-bench_runs/r05_watchdog.log}
cd "$(dirname "$0")/.." || exit 1
mkdir -p bench_runs
i=0
broken=0
while :; do
  i=$((i + 1))
  timeout 240 python -c "import jax, jax.numpy as jnp; print(float((jnp.ones((256,256))@jnp.ones((256,256))).sum()))" >>"$LOG" 2>&1
  rc=$?
  if [ "$rc" -eq 0 ]; then
    echo "[watch] tunnel alive at probe $i $(date '+%F %T')" >>"$LOG"
    SWEEP_RUN_TIMEOUT=${SWEEP_RUN_TIMEOUT:-700} \
      python tools/mfu_sweep.py "$OUT" >>"$LOG" 2>&1
    echo "[watch] sweep finished $(date '+%F %T')" >>"$LOG"
    exit 0
  fi
  # 124/137: the probe TIMED OUT (wedged tunnel) -> keep waiting.  Any
  # other rc is the probe itself failing (no python, broken jax, bad
  # env); retrying that forever would silently skip the round's
  # measurements — abort loudly after 3 in a row instead.
  if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    broken=0
    echo "[watch] probe $i: tunnel dead $(date '+%F %T'); retry in 240s" >>"$LOG"
  else
    broken=$((broken + 1))
    echo "[watch] probe $i: probe FAILED rc=$rc (not a timeout) $(date '+%F %T')" >>"$LOG"
    if [ "$broken" -ge 3 ]; then
      echo "[watch] aborting: probe broken (rc=$rc) 3x in a row" >>"$LOG"
      exit 1
    fi
  fi
  sleep 240
done
