#!/usr/bin/env python
"""check_env_docs — assert every BYTEPS_TPU_* knob is documented.

Every ``BYTEPS_TPU_*`` environment variable read anywhere under
``byteps_tpu/`` (Python or C++) must have a row (or at least a mention)
in ``docs/env.md`` — and every ``BYTEPS_TPU_*`` name docs/env.md
mentions must still exist in the code.  Undocumented knobs are how
operators end up reading source to configure a job, and stale docs are
how they set knobs that silently do nothing; both directions drift one
PR at a time unless a test pins them.

Wired as a fast tier-1 test (tests/test_env_docs.py); also runnable
standalone:

    python tools/check_env_docs.py [repo_root]

Exit 0 = in sync; 1 = drift (each missing name printed with where it
was seen).
"""

from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Set

ENV_RE = re.compile(r"BYTEPS_TPU_[A-Z0-9_]+")

# Names that LOOK like knobs to the regex but are not real environment
# variables: prefixes used in prose ("the BYTEPS_TPU_MESH_* family") or
# incomplete stems.  Keep this list short and literal — every entry is a
# hole in the check.
IGNORE = {
    "BYTEPS_TPU_MESH_",      # prose referring to the family
    "BYTEPS_TPU_",           # bare prefix in prose
}

# tools/ counts as code too: developer-facing knobs like
# BYTEPS_TPU_TEST_BUDGET_S live only there, and an env.md row for a
# name no code reads is exactly the drift this check exists to catch.
CODE_DIRS = ("byteps_tpu", "tools")
CODE_EXTS = (".py", ".cc", ".h")
DOC_FILE = os.path.join("docs", "env.md")

# Global knobs the CMD_KNOB plane actuates mid-job.  Each must be
# documented in docs/performance.md WITH its apply-boundary semantics
# ("round boundary" in the same paragraph): an actuated knob documented
# without "when does it land" reads as instant — and instant is exactly
# what the epoch law exists to prevent.  A knob added to the actuated
# set without boundary docs is the drift this check pins.
ACTUATED_KNOBS = ("BYTEPS_TPU_FUSION_BYTES",
                  "BYTEPS_TPU_COMPRESS_THREADS",
                  "BYTEPS_TPU_WIRE_CONNS")
PERF_DOC = os.path.join("docs", "performance.md")
BOUNDARY_RE = re.compile(r"round\s+boundary", re.IGNORECASE)


def _names_in_file(path: str) -> Set[str]:
    try:
        with open(path, errors="replace") as f:
            text = f.read()
    except OSError:
        return set()
    return {m for m in ENV_RE.findall(text) if m not in IGNORE
            and not m.endswith("_")}


def scan_code(root: str) -> Dict[str, List[str]]:
    """{env_name: [files mentioning it]} across the package sources."""
    out: Dict[str, List[str]] = {}
    for d in CODE_DIRS:
        for dirpath, _dirnames, filenames in os.walk(os.path.join(root,
                                                                  d)):
            for fn in filenames:
                if not fn.endswith(CODE_EXTS):
                    continue
                p = os.path.join(dirpath, fn)
                for name in _names_in_file(p):
                    out.setdefault(name, []).append(
                        os.path.relpath(p, root))
    return out


def scan_docs(root: str) -> Set[str]:
    return _names_in_file(os.path.join(root, DOC_FILE))


def check(root: str) -> List[str]:
    """Drift report lines; empty = in sync."""
    code = scan_code(root)
    docs = scan_docs(root)
    problems = []
    for name in sorted(set(code) - docs):
        problems.append(
            f"UNDOCUMENTED: {name} is read in "
            f"{', '.join(sorted(code[name])[:3])} but has no row in "
            f"{DOC_FILE}")
    for name in sorted(docs - set(code)):
        problems.append(
            f"STALE DOC: {name} appears in {DOC_FILE} but nothing under "
            f"{CODE_DIRS[0]}/ reads it")
    problems += check_knob_boundaries(root)
    return problems


def check_knob_boundaries(root: str) -> List[str]:
    """Every actuated global knob must state its apply-boundary
    semantics ("round boundary") in the docs/performance.md paragraph
    that mentions it."""
    try:
        with open(os.path.join(root, PERF_DOC), errors="replace") as f:
            text = f.read()
    except OSError:
        return [f"MISSING: {PERF_DOC} (actuated-knob boundary docs "
                f"live there)"]
    problems = []
    for knob in ACTUATED_KNOBS:
        paras = [p for p in text.split("\n\n") if knob in p]
        if not paras:
            problems.append(
                f"KNOB UNDOCUMENTED: actuated knob {knob} is never "
                f"mentioned in {PERF_DOC} — the knob plane applies it "
                f"mid-job, so its docs must say when it lands")
        elif not any(BOUNDARY_RE.search(p) for p in paras):
            problems.append(
                f"KNOB BOUNDARY UNDOCUMENTED: {knob} is mentioned in "
                f"{PERF_DOC} but no paragraph naming it states its "
                f"apply-boundary ('round boundary') semantics")
    return problems


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    root = args[0] if args else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    problems = check(root)
    if problems:
        print("\n".join(problems))
        print(f"\n{len(problems)} env-doc drift problem(s); every "
              f"BYTEPS_TPU_* knob must appear in {DOC_FILE} (and vice "
              f"versa)")
        return 1
    print("env docs in sync")
    return 0


if __name__ == "__main__":
    sys.exit(main())
