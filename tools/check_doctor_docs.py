#!/usr/bin/env python
"""check_doctor_docs — assert the doctor's rules and the playbook agree.

Every doctor finding carries a playbook anchor
(``docs/troubleshooting.md#rule-<id>``); an anchor that doesn't exist
sends an operator mid-incident to a dead link, and a playbook entry for
a deleted rule documents behavior that can never fire.  Modeled on
``tools/check_env_docs.py``: both directions are pinned as a fast
tier-1 test (tests/test_doctor_docs.py) so they can't drift one PR at a
time.

  - every rule id in ``byteps_tpu.common.doctor.RULE_IDS`` must have a
    ``<a id="rule-<id>"></a>`` anchor in docs/troubleshooting.md;
  - every ``rule-*`` anchor in docs/troubleshooting.md must name a
    live rule.

Also runnable standalone::

    python tools/check_doctor_docs.py [repo_root]

Exit 0 = in sync; 1 = drift (each problem printed).
"""

from __future__ import annotations

import os
import re
import sys
from typing import List

ANCHOR_RE = re.compile(r'<a id="rule-([a-z0-9_]+)">')
DOC_FILE = os.path.join("docs", "troubleshooting.md")


def _rule_ids(root: str) -> List[str]:
    if root not in sys.path:
        sys.path.insert(0, root)
    from byteps_tpu.common.doctor import RULE_IDS
    return list(RULE_IDS)


def _doc_anchors(root: str) -> List[str]:
    try:
        with open(os.path.join(root, DOC_FILE), errors="replace") as f:
            return ANCHOR_RE.findall(f.read())
    except OSError:
        return []


def check(root: str) -> List[str]:
    """Drift report lines; empty = in sync."""
    rules = set(_rule_ids(root))
    anchors = _doc_anchors(root)
    problems = []
    for rid in sorted(rules - set(anchors)):
        problems.append(
            f'MISSING PLAYBOOK: doctor rule "{rid}" has no '
            f'<a id="rule-{rid}"> anchor in {DOC_FILE} — its findings '
            f'link to a dead anchor')
    for a in sorted(set(anchors) - rules):
        problems.append(
            f'STALE PLAYBOOK: {DOC_FILE} anchors "rule-{a}" but no '
            f'doctor rule with that id exists')
    dup = sorted({a for a in anchors if anchors.count(a) > 1})
    for a in dup:
        problems.append(
            f'DUPLICATE ANCHOR: "rule-{a}" appears more than once in '
            f'{DOC_FILE} — fragment links resolve to the first only')
    return problems


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    root = args[0] if args else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    problems = check(root)
    if problems:
        print("\n".join(problems))
        print(f"\n{len(problems)} doctor-doc drift problem(s); every "
              f"rule id must have a matching anchor in {DOC_FILE} "
              f"(and vice versa)")
        return 1
    print("doctor docs in sync")
    return 0


if __name__ == "__main__":
    sys.exit(main())
