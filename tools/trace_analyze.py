#!/usr/bin/env python
"""trace_analyze — critical-path breakdown of a merged byteps_tpu trace.

Reads one or more merged ``comm.json`` files (worker spans + server spans
on one aligned clock, see docs/timeline.md) and prints, per step: the
critical partition chain and a queue / encode / wire / server merge-wait /
sum / decode breakdown that sums to the measured step time — plus top-k
blocking tensors (with fused-bucket member attribution) and per-worker
straggler attribution from the server MERGE_WAIT spans.

Usage:
    python tools/trace_analyze.py traces/0/comm.json
    python tools/trace_analyze.py traces/*/comm.json --worker 0 --top 10
    python tools/trace_analyze.py traces/0/comm.json --json

Multiple files merge before analysis: in a multi-worker run each server
span is drained by exactly one worker, so pass every worker's file to see
the whole fleet.  No dependencies beyond the stdlib + byteps_tpu.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from byteps_tpu.common import trace_analysis  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="+", help="merged comm.json file(s)")
    ap.add_argument("--worker", type=int, default=0,
                    help="whose chain to walk (default rank 0)")
    ap.add_argument("--top", type=int, default=5,
                    help="top-k blocking tensors (default 5)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable result instead of the report")
    args = ap.parse_args(argv)

    events = []
    for path in args.files:
        with open(path) as f:
            doc = json.load(f)
        events.extend(doc.get("traceEvents", []))
    if not events:
        print("no trace events found", file=sys.stderr)
        return 1
    result = trace_analysis.analyze(events, worker=args.worker,
                                    top_k=args.top)
    if args.json:
        print(json.dumps(result, indent=2))
    else:
        print(trace_analysis.format_report(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
