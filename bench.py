"""Benchmark: flagship (BERT-large-class) DP training throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference's headline number is ~90% scaling efficiency for BERT-large
DP training (reference: README.md:38-46, BASELINE.md).  Scaling efficiency
is throughput-with-the-framework / ideal-throughput; on a single chip the
ideal is the raw jitted train step with no distribution framework, so
`efficiency = framework_step_throughput / raw_step_throughput` measured on
the same hardware — the framework's communication/scheduling overhead is
exactly what scaling efficiency penalises at scale.  vs_baseline =
efficiency / 0.90 (the reference's 256-GPU result; >1.0 beats it).

detail carries tokens/sec/chip and MFU (6·N·tokens/s over the chip's peak
bf16 FLOPs — the scaling-book utilization metric).

Modes:
  (default)          flagship efficiency bench (framework path donates its
                     buffers, the deployment configuration)
  BENCH_MACHINERY=1  communication-machinery bench on the device mesh:
                     naive tree_all_reduce vs bucketed vs hierarchical
                     (reference analog: example/pytorch/benchmark_byteps.py
                     measuring the framework's own data path)
  BENCH_WIRE=1       raw-speed acceptance: PS goodput as pct_of_floor of
                     the same-host raw socket echo floor (wire_bench.py
                     --echo-floor; BENCH_WIRE_UDS=1 for the AF_UNIX path)
  BENCH_PS=1         PS wire goodput through the real C++ server over
                     loopback TCP (reference analog: the ps-lite transport
                     benchmark in .travis.yml:29-34)
  BENCH_FAULT=1      fault-tolerance bench: mid-round connection reset via
                     tools/chaos_proxy.py; emits fault_reconnect_recovery_ms
  BENCH_ELASTIC=1    elastic-membership bench: permanent worker kill +
                     replacement join; emits evict_detect_ms and
                     join_catchup_ms (BENCH_ELASTIC_EVICT_S tunes the lease)
  BENCH_FUSION=1     fusion-layer wire bench: many small tensors, per-leaf
                     vs fused-bucket dispatch through the real PS server
                     (emits fusion_small_tensor_caller_block)
  BENCH_TRACE=1      tracing-overhead bench: sync-round time with the
                     distributed tracer hot (worker+server spans, traced
                     wire flags) vs off (emits trace_overhead_ms)
  BENCH_AUDIT=1      auditor-overhead bench: sync-round time with the
                     consistency auditor hot (publish digests, pull
                     trailers, re-digest, health sampling) vs off —
                     audit_overhead_ms, expected within noise
  BENCH_DOCTOR=1     signal-plane/doctor-overhead bench: sync-round time
                     with the windowed key-signal plane + doctor rules
                     hot vs off, plus the per-window roll cost
  BENCH_FLEET=1      fleet-plane bench: sync-round time with CMD_WINDOW
                     publishing + CMD_FLEET fetching hot per window vs
                     off; emits fleet_plane_overhead_ms and the goodput
                     ledger's fleet_goodput_pct over the live merged view
  BENCH_AUTOTUNE=1   adaptive-compression bench: the same mixed-key
                     workload UNTUNED-with-tuner (starts raw, the tuner
                     renegotiates codecs live off the signal plane) vs
                     HAND-TUNED (codecs registered up front); emits
                     autotune_step_time_gap_pct (target: within a few %)
                     plus switch counts and the per-key final codec
                     assignments
  BENCH_KNOB=1       knob-plane bench: cold-start job whose predictive
                     tuner must discover FUSION_BYTES + codecs live
                     (cost-model jumps + actuated CMD_KNOB sets at
                     round boundaries) vs the hand-tuned expert config;
                     emits knob_step_time_gap_pct (target: <= 0) with
                     the cost-model seed and final knob assignments
  BENCH_SERVEROPT=1  server-resident-optimizer bench: the same Adam
                     workload with the update stage on the PS tier
                     (push grads, pull params) vs worker-local optax;
                     emits serveropt_step_time_gap_pct plus the
                     structural detail (worker optimizer-state bytes ->
                     0 in server mode, param_version == rounds)
  BENCH_HIER=1       hierarchical-reduction bench: the same 4-worker
                     sync workload flat vs 2-slice x 2-chip (in-graph
                     psum intra-slice, leaders-only on the wire;
                     BENCH_HIER_SLICE overrides the slice size); emits
                     hier_wire_bytes_saved_pct plus the per-worker wire
                     bytes and step-time deltas
  BENCH_TELEMETRY=1  telemetry-overhead bench: sync-round time with the
                     metrics endpoint scraped at 20Hz vs export plane off
                     (emits telemetry_overhead_ms; expected within noise)
  BENCH_CNN=<name>   image-model throughput (resnet50 / vgg16 ...), fp32 —
                     the reference's other headline rows (reference:
                     docs/performance.md:5-26); BENCH_CNN_BATCH per chip
  BENCH_SMALL=1      shrink the model for quick local runs
  BENCH_FORCE_CPU=1  8 virtual CPU devices

Sweep knobs (tools/mfu_sweep.py): BENCH_MODEL picks any named config
(e.g. llama_300m), BENCH_SEQ overrides its sequence length, BENCH_BATCH /
BENCH_ATTN / BENCH_ATTN_BLOCK / BENCH_ATTN_BLOCK_K (decoupled K/V tile) /
BENCH_REMAT / BENCH_REMAT_POLICY / BENCH_CE_CHUNK / BENCH_UNROLL
(layer-scan unroll) override the rest of the geometry.  BENCH_COST=1
adds XLA's compile-time accounting (flops, HBM bytes, arithmetic
intensity) for the raw single-chip step to the JSON detail — off by
default because the AOT re-lower is a fresh-compile risk on a flaky
tunnel.

Runs on whatever jax.devices() offers: the real TPU chip under the driver,
or the 8-device virtual CPU mesh locally.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

def _param_count(params) -> int:
    import jax
    return sum(int(l.size) for l in jax.tree.leaves(params))


def _peak_flops(device) -> float:
    """Peak dense bf16 FLOPs/s for a device.  The spec-sheet table and
    the env override live in byteps_tpu.common.devprof now (PR 20) —
    ONE table shared with the live MFU gauges, so bench MFU and
    `bps_mfu` can never disagree on a platform's peak.  Lazy import:
    bench.py's module load must stay side-effect-free for the hermetic
    subprocess benches."""
    from byteps_tpu.common.devprof import peak_flops
    return peak_flops(device)


def _device_stamp() -> dict:
    """Platform-honesty stamp for every BENCH record (ROADMAP: BENCH_r05
    silently recorded CPU-fallback numbers that read like on-chip ones).

    The detector itself moved to byteps_tpu.common.devprof (PR 20): the
    live doctor's device sentinel probes the SAME function every signal
    window, so the bench-time stamp and the runtime verdict cannot
    drift.  Semantics unchanged — see devprof.device_stamp."""
    from byteps_tpu.common.devprof import device_stamp
    return device_stamp()


def _note() -> dict:
    """Provenance for the detail payload: the CPU-fallback note plus the
    device-platform honesty stamp (every BENCH record carries both)."""
    n = os.environ.get("BENCH_NOTE")
    return {**({"note": n} if n else {}), **_device_stamp()}


def _headline_note() -> dict:
    """`_note()` for HEADLINE records (flagship / MULTICHIP / CNN): a
    run that silently fell back to the CPU host REFUSES to write the
    record at all — BENCH_r05's fallback number sat in the history
    reading like an on-chip result for a whole round, and the unit
    prefix alone did not stop it.  `BENCH_ALLOW_FALLBACK=1` is the
    explicit override: the record is then written stamped
    `"fallback": true` so no downstream reader can mistake it.
    Host-only benches (wire/fault/telemetry/audit/...) keep plain
    `_note()` — they never involve a device, so there is nothing to
    fall back from."""
    n = _note()
    if n.get("device_fallback"):
        if os.environ.get("BENCH_ALLOW_FALLBACK", "0") != "1":
            _error_record(
                "device_fallback detected — REFUSING to write a headline "
                "BENCH record from a CPU-fallback run (the r05 silent-CPU "
                "failure mode).  Fix the device tunnel, or set "
                "BENCH_ALLOW_FALLBACK=1 to record it stamped "
                "\"fallback\": true")
            raise SystemExit(3)
        n["fallback"] = True
    return n


def _headline(unit: str, vs_baseline: float) -> dict:
    """Headline {unit, vs_baseline}, marked when this process is the
    hermetic CPU-fallback child.  Contract (round-4 review): a driver
    parsing only {rc, value, vs_baseline} must never mistake a fallback
    for an on-chip measurement — a tiny-model CPU run's vs_baseline of
    ~1.1 reads exactly like a passing flagship number.  So the fallback's
    unit gains a `cpu_fallback_` prefix and vs_baseline is zeroed; the
    detail note + last_onchip_archive pointer still carry the human
    story.  An EXPLICIT local CPU run (BENCH_FORCE_CPU, used by tests
    and dev loops) is not a fallback and keeps the plain headline."""
    if os.environ.get("BENCH_CPU_FALLBACK_CHILD", "0") == "1":
        return {"unit": f"cpu_fallback_{unit}", "vs_baseline": 0.0}
    return {"unit": unit, "vs_baseline": vs_baseline}


def _time_steps(fn, params, opt_state, batch, n, per_step):
    """Shared timing harness: warmup+compile step, then n timed steps.

    `fn(params, opt_state, batch) -> (params, opt_state, loss)`; returns
    units/sec where one step advances `per_step` units (tokens, images).
    The `float(loss)` every step is a HARD device sync — async runtimes
    (and the axon relay, where block_until_ready does not force chained
    execution) otherwise report dispatch rate, not execution rate.
    """
    params, opt_state, loss = fn(params, opt_state, batch)
    float(loss)  # warmup + compile
    t0 = time.perf_counter()
    for _ in range(n):
        params, opt_state, loss = fn(params, opt_state, batch)
        float(loss)
    return n * per_step / (time.perf_counter() - t0)


def _attn_block_for(seq: int) -> int:
    """BENCH_ATTN_BLOCK, normalized to the kernel's auto choice when unset
    or when the kernel would reject it (must divide seq and be a multiple
    of 64) — so the JSON label always states the block that actually ran.
    The auto rule is imported, not duplicated, so record and kernel can't
    drift."""
    from byteps_tpu.models.transformer import flash_auto_block
    ab = int(os.environ.get("BENCH_ATTN_BLOCK", "0"))
    if ab and seq % ab == 0 and ab % 64 == 0:
        return ab
    return flash_auto_block(seq)


def _cfg_with_env_overrides(cfg, seq: int, default_attn: str = ""):
    """Apply the sweep env knobs (BENCH_ATTN / BENCH_ATTN_BLOCK /
    BENCH_REMAT / BENCH_REMAT_POLICY) to a model config — one parser for
    every bench branch so the knobs can't silently diverge.  Defaults
    come from the config itself unless `default_attn` pins a different
    attention choice (the flagship default)."""
    attn = os.environ.get("BENCH_ATTN", default_attn or cfg.attn_impl)
    if attn == "flash" and _attn_block_for(seq) == 0:
        # flash_attention_fn would silently fall back to dense here and
        # the record would archive dense throughput under a flash label —
        # an invalid sweep geometry must fail loudly instead.
        raise SystemExit(f"BENCH_ATTN=flash needs seq divisible by 64 "
                         f"(got BENCH_SEQ/seq={seq})")
    bk = 0
    if attn == "flash":
        # Same normalize-to-auto contract as BENCH_ATTN_BLOCK: an invalid
        # K tile reverts to the Q tile (exactly what the adapter would
        # run), and the knob is ignored entirely off the flash path.
        bk = int(os.environ.get("BENCH_ATTN_BLOCK_K", "0"))
        if bk and (seq % bk or bk % 64):
            bk = 0
    return dataclasses.replace(
        cfg, attn_impl=attn,
        # BENCH_REMAT=0 disables per-layer remat entirely (viable only
        # when the config avoids the S^2 logits, i.e. with flash, and at
        # batches where saved activations fit HBM).
        remat=(os.environ["BENCH_REMAT"] != "0"
               if "BENCH_REMAT" in os.environ else cfg.remat),
        remat_policy=os.environ.get("BENCH_REMAT_POLICY", cfg.remat_policy),
        # Gate on flash so the record never carries a block the dense
        # path silently ignored.
        attn_block=_attn_block_for(seq) if attn == "flash" else 0,
        attn_block_k=bk if attn == "flash" else 0,
        # BENCH_UNROLL=k groups k layers per scan iteration (must divide
        # num_layers — the config validates, so a bad sweep value fails
        # loudly rather than silently benching unroll=1).
        scan_unroll=int(os.environ.get("BENCH_UNROLL", "0")) or
        cfg.scan_unroll)


def bench_flagship():
    import jax
    import optax

    import byteps_tpu as bps
    from byteps_tpu.models import transformer as tfm

    on_tpu = jax.devices()[0].platform == "tpu"
    alt_model = os.environ.get("BENCH_MODEL", "")
    # An explicit BENCH_MODEL is honored on any backend (llama_tiny is
    # CPU-feasible); only the implicit off-TPU fallback forces tiny.
    small = (os.environ.get("BENCH_SMALL", "0") == "1"
             or (not on_tpu and not alt_model))
    ce_chunk = int(os.environ.get("BENCH_CE_CHUNK", "2048"))
    if small:
        cfg = tfm.get_config("tiny", causal=True)
        batch, seq, steps = 8 * max(1, jax.device_count()), 128, 5
    elif alt_model:
        # Bench any named config (e.g. BENCH_MODEL=llama_1b for the
        # modern-LLM block) at its native sequence length.  The streamed
        # LM head applies here too (llama_1b's full logits at seq 2048
        # would be 2.1 GB of f32 HBM traffic).  BENCH_ATTN / _ATTN_BLOCK /
        # _REMAT_POLICY / _BATCH override the config's defaults so sweeps
        # (e.g. the long-seq block question in tools/mfu_sweep.py) can
        # run on these geometries too.
        cfg = tfm.get_config(alt_model, causal=True, ce_chunk_rows=ce_chunk)
        seq = int(os.environ.get("BENCH_SEQ", "0")) \
            or min(cfg.max_seq_len, 2048)
        if seq > cfg.max_seq_len:
            cfg = dataclasses.replace(cfg, max_seq_len=seq)
        cfg = _cfg_with_env_overrides(cfg, seq)
        batch = int(os.environ.get("BENCH_BATCH",
                                   "8")) * jax.device_count()
        steps = 10
    else:
        # Full BERT-large geometry (reference benchmark: README.md:38-46),
        # causal-LM objective, bf16 activations, per-layer remat, streamed
        # LM-head cross-entropy.  Round-4 on-chip sweep
        # (bench_runs/r04_sweep{1,2}.jsonl): flash attention with a
        # full-sequence 512 block beats XLA's dense fusion at this size
        # (33.7k vs 30.6k tok/s at batch 48 — the old "0.91x at seq 512"
        # guidance was measured at batch 16), and batch 64 under flash
        # adds another 2% -> 34.3k tok/s, MFU 0.352 (dense at batch 64 is
        # unmeasured).  Each knob stays env-overridable for sweeps:
        # BENCH_CE_CHUNK=0 / BENCH_ATTN=dense / BENCH_REMAT_POLICY=proj /
        # BENCH_BATCH=48.
        cfg = tfm.get_config(
            "bert_large", causal=True, vocab_size=32768, max_seq_len=512,
            ce_chunk_rows=ce_chunk)
        cfg = _cfg_with_env_overrides(cfg, 512, default_attn="flash")
        batch = int(os.environ.get("BENCH_BATCH", "64")) * jax.device_count()
        seq, steps = 512, 10

    mesh = bps.make_mesh()  # all devices on dp
    params = tfm.init_params(jax.random.key(0), cfg)
    n_params = _param_count(params)
    toks, tgts = tfm.synthetic_batch(jax.random.key(1), batch, seq, cfg)

    def loss_fn(p, b):
        return tfm.loss_fn(p, b, cfg)

    # Framework path: DistributedOptimizer (bucketed priority all-reduce),
    # donated buffers — the deployment configuration.  Donation consumes
    # the input arrays, so the framework path runs on its own copies and
    # the raw path keeps the originals.
    import jax.numpy as jnp
    opt = bps.DistributedOptimizer(optax.adamw(1e-4))
    step = bps.build_train_step(loss_fn, opt, mesh, donate=True)
    fw_tps = _time_steps(step, jax.tree.map(jnp.copy, params),
                         opt.init(params), (toks, tgts), steps, batch * seq)

    # Ideal path: same model/optimizer, no distribution framework, one shard
    # of the global batch on one device -> ideal per-chip throughput.
    raw_opt = optax.adamw(1e-4)
    n_dev = jax.device_count()
    rb = max(1, batch // n_dev)

    def raw_step(p, s, b):
        loss, g = jax.value_and_grad(loss_fn)(p, b)
        u, s = raw_opt.update(g, s, p)
        return optax.apply_updates(p, u), s, loss

    rstep = jax.jit(raw_step, donate_argnums=(0, 1))
    raw_state = raw_opt.init(params)
    # Abstract arg shapes captured before timing donates the buffers —
    # BENCH_COST re-lowers from these (cache-warm) for cost_analysis.
    abs_args = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
        (params, raw_state, (toks[:rb], tgts[:rb])))
    raw_tps = _time_steps(rstep, params, raw_state,
                          (toks[:rb], tgts[:rb]), steps, rb * seq)

    cost = {}
    if os.environ.get("BENCH_COST", "0") == "1":
        # XLA's compile-time accounting for the single-chip step: total
        # flops and HBM bytes accessed -> arithmetic intensity and which
        # roofline (compute vs bandwidth) the config sits under.  Off by
        # default: the AOT lower/compile is normally a cache hit but any
        # fresh remote compile is a tunnel-wedge risk (pass-2 postmortem),
        # so only sweeps ask for it.
        try:
            ca = rstep.lower(*abs_args).compile().cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
            flops = float(ca.get("flops", 0.0))
            hbm = float(ca.get("bytes accessed", 0.0))
            cost = {"xla_flops_per_raw_step": flops,
                    "xla_hbm_bytes_per_raw_step": hbm}
            if hbm > 0:
                cost["arithmetic_intensity"] = round(flops / hbm, 2)
        except Exception as e:   # never let accounting kill the bench
            cost = {"cost_analysis_error": repr(e)[:200]}

    efficiency = fw_tps / (raw_tps * n_dev)
    tps_per_chip = fw_tps / n_dev
    peak = _peak_flops(jax.devices()[0])
    mfu = (6.0 * n_params * tps_per_chip / peak) if peak else 0.0
    model_name = ("tiny" if small else (alt_model or "bert_large"))
    print(json.dumps({
        "metric": f"{model_name}_dp_scaling_efficiency",
        "value": round(efficiency, 4),
        **_headline("fraction_of_ideal", round(efficiency / 0.90, 4)),
        "detail": {
            "framework_tokens_per_sec": round(fw_tps),
            "tokens_per_sec_per_chip": round(tps_per_chip),
            "ideal_tokens_per_sec_per_chip": round(raw_tps),
            "mfu": round(mfu, 4),
            "params": n_params,
            "peak_bf16_flops": peak,
            "donate": True,
            "devices": n_dev,
            "batch": batch, "seq": seq,
            "model": model_name,
            "ce_chunk_rows": cfg.ce_chunk_rows,
            "attn_impl": cfg.attn_impl,
            "attn_block": cfg.attn_block,
            "attn_block_k": cfg.attn_block_k or cfg.attn_block,
            "remat": cfg.remat,
            "remat_policy": cfg.remat_policy,
            "scan_unroll": cfg.scan_unroll,
            **cost,
            **_headline_note(),
        },
    }))


def bench_cnn():
    """Image-model DP training throughput: full framework path vs the
    raw-jit roofline, images/sec.

    Mirrors the reference's other headline rows — ResNet-50 / VGG-16
    throughput at BS=64/GPU, fp32 (reference: docs/performance.md:5-26,
    BASELINE.md) — with the flagship bench's methodology: identical
    model/optimizer on both sides of the ratio, hard device sync every
    step, efficiency = framework / ideal and vs_baseline against the
    reference's 0.90 scaling-efficiency bar.  fp32 like the reference
    rows (the MXU runs f32 matmuls in multi-pass emulation, so absolute
    images/sec is conservative; the RATIO is what the metric carries).
    """
    import jax
    import jax.numpy as jnp
    import optax

    import byteps_tpu as bps
    from byteps_tpu import models

    name = os.environ.get("BENCH_CNN", "resnet50")
    on_tpu = jax.devices()[0].platform == "tpu"
    small = os.environ.get("BENCH_SMALL", "0") == "1" or not on_tpu
    if small:
        # CPU-feasible stand-in keeping the same code path: shallow
        # member of the same family, CIFAR-sized images.
        name = "vgg16" if "vgg" in name else "resnet18"
        batch_per, hw, steps = 8, 32, 3
    else:
        batch_per = int(os.environ.get("BENCH_CNN_BATCH", "64"))
        hw, steps = 224, 10
    n_dev = jax.device_count()
    batch = batch_per * n_dev

    # dtype=f32 explicitly: the model zoo defaults to bf16 compute, but
    # the reference rows being mirrored are fp32.
    model = models.create_cnn(name, num_classes=1000, dtype=jnp.float32)
    x0 = jnp.ones((2, hw, hw, 3), jnp.float32)
    variables = model.init(jax.random.key(0), x0, train=False)
    n_params = _param_count(variables)
    loss_fn = models.cnn_loss_fn(model)
    images = jax.random.normal(jax.random.key(1), (batch, hw, hw, 3),
                               jnp.float32)
    labels = jax.random.randint(jax.random.key(2), (batch,), 0, 1000)

    mesh = bps.make_mesh()
    opt = bps.DistributedOptimizer(optax.sgd(0.1, momentum=0.9))
    step = bps.build_train_step(loss_fn, opt, mesh, donate=True)
    fw_ips = _time_steps(step, jax.tree.map(jnp.copy, variables),
                         opt.init(variables), (images, labels), steps, batch)

    raw_opt = optax.sgd(0.1, momentum=0.9)

    def raw_step(v, s, b):
        loss, g = jax.value_and_grad(loss_fn)(v, b)
        u, s = raw_opt.update(g, s, v)
        return optax.apply_updates(v, u), s, loss

    rb = max(1, batch // n_dev)
    rstep = jax.jit(raw_step, donate_argnums=(0, 1))
    raw_ips = _time_steps(rstep, variables, raw_opt.init(variables),
                          (images[:rb], labels[:rb]), steps, rb)

    efficiency = fw_ips / (raw_ips * n_dev)
    print(json.dumps({
        "metric": f"{name}_dp_scaling_efficiency",
        "value": round(efficiency, 4),
        **_headline("fraction_of_ideal", round(efficiency / 0.90, 4)),
        "detail": {
            "framework_images_per_sec": round(fw_ips, 1),
            "images_per_sec_per_chip": round(fw_ips / n_dev, 1),
            "ideal_images_per_sec_per_chip": round(raw_ips, 1),
            "params": n_params,
            "devices": n_dev,
            "batch": batch, "image_size": hw,
            "model": name, "dtype": "float32",
            **_headline_note(),
        },
    }))


def bench_machinery():
    """Measure the framework's own collective machinery: naive one-psum-per
    -leaf vs bucketed vs hierarchical tree all-reduce on the device mesh.

    Two regimes, both reported:
      - small_leaves (headline): thousands of small gradients — the DNN
        gradient-list regime bucketing was built for; per-collective
        overhead dominates, fewer+larger transfers win (reference analog:
        the packing rationale of cross_device_ops.py:251-296).
      - mixed: realistic large+small mix.  On a virtual CPU mesh the
        pack/unpack copies are the dominant cost and bucketing roughly
        ties; on real ICI the per-collective latency it removes is far
        larger, which is why 4MB bucketing is the deployment default.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import byteps_tpu as bps
    from byteps_tpu.ops import collectives

    n_dev = jax.device_count()
    mesh = bps.make_mesh()
    ici = max(1, n_dev // 2)
    hmesh = bps.make_hierarchical_mesh(ici)
    rng = jax.random.key(0)

    def make_tree(sizes):
        leaves = [jax.random.normal(jax.random.fold_in(rng, i), (s,),
                                    dtype=jnp.float32)
                  for i, s in enumerate(sizes)]
        return {f"g{i}": l for i, l in enumerate(leaves)}

    def timed(mesh_, fn, tree, reps=5):
        sm = jax.jit(jax.shard_map(
            fn, mesh=mesh_, in_specs=(P(),), out_specs=P(),
            check_vma=False))
        jax.block_until_ready(sm(tree))  # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(sm(tree))
            best = min(best, time.perf_counter() - t0)
        return best

    def run_regime(sizes):
        tree = make_tree(sizes)
        t_naive = timed(mesh, lambda t: collectives.tree_all_reduce(t, "dp"),
                        tree)
        t_bucket = timed(
            mesh, lambda t: collectives.bucketed_tree_all_reduce(t, "dp"),
            tree)
        t_hier = timed(
            hmesh,
            lambda t: collectives.hierarchical_tree_all_reduce(t), tree)
        return {
            "naive_ms": round(t_naive * 1e3, 3),
            "bucketed_ms": round(t_bucket * 1e3, 3),
            "hierarchical_ms": round(t_hier * 1e3, 3),
            "bucketed_speedup": round(t_naive / t_bucket, 4),
            "leaves": len(sizes),
            "mbytes": round(sum(sizes) * 4 / 1e6, 1),
        }

    small = run_regime([1_000] * 2000)
    mixed = run_regime([1_000] * 150 + [50_000] * 30 + [1_000_000] * 4)
    print(json.dumps({
        "metric": "machinery_bucketed_speedup_vs_naive",
        "value": small["bucketed_speedup"],
        # >1.0: bucketing pays
        **_headline("x", small["bucketed_speedup"]),
        "detail": {
            "small_leaves": small,
            "mixed": mixed,
            "devices": n_dev,
            "ici_size": ici,
            **_headline_note(),
        },
    }))


def bench_fusion():
    """Fusion-layer wire benchmark: the many-small-tensors regime through
    the real PS server (tools/wire_bench.py fusion_ab), emitted as the
    `fusion_small_tensor_caller_block` metric so BENCH_r* tracks the
    trajectory.

    value = the fused caller-block wall time for one round of the
    many-small-tensors scenario (512 leaves of 4-64 KiB; 128 with
    BENCH_SMALL=1); vs_baseline = the per-leaf (unfused) caller-block
    time over it — how many times faster the caller gets back to its
    step compute with the fusion layer on.  Host-only, like BENCH_PS.
    """
    import subprocess
    import sys

    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "wire_bench.py")
    argv = [sys.executable, tool, "--fusion-only", "--json"]
    if os.environ.get("BENCH_SMALL", "0") == "1":
        argv.append("--quick")
    r = subprocess.run(argv, capture_output=True, text=True, timeout=1200)
    if r.returncode != 0:
        _error_record(f"fusion bench failed rc={r.returncode}: "
                      f"{r.stderr[-400:]}")
        raise SystemExit(3)
    fus = json.loads(r.stdout)["fusion"]
    print(json.dumps({
        "metric": "fusion_small_tensor_caller_block",
        "value": round(fus["fused"]["caller_block_best_s"] * 1e3, 3),
        "unit": "ms",
        "vs_baseline": fus["caller_block_speedup"],
        "detail": {
            "num_leaves": fus["num_leaves"],
            "leaf_kb": fus["leaf_kb"],
            "total_mb": fus["total_mb"],
            "fusion_bytes": fus["fusion_bytes"],
            "wire_message_reduction": fus["wire_message_reduction"],
            "sync_round_speedup": fus["sync_round_speedup"],
            "priority_descending": fus["priority_descending"],
            "unfused_caller_block_ms": round(
                fus["unfused"]["caller_block_best_s"] * 1e3, 3),
            "unfused_msgs_per_round":
                fus["unfused"]["wire_messages_per_round"],
            "fused_msgs_per_round":
                fus["fused"]["wire_messages_per_round"],
            "buckets": fus["fused"]["buckets"],
            "note": "vs_baseline = unfused/fused caller-block time; "
                    "wire messages are PUSH dispatches per round "
                    "(PULLs mirror 1:1)",
            **_note(),
        },
    }))


def _boot_ps_server(engine_threads: int, num_workers: int = 1,
                    extra_env: dict = None):
    """Start the native PS server on a freshly-probed free port, retrying
    on a new port if another process snatches it (bind/close-then-launch
    is inherently TOCTOU on a busy host).  Returns (proc, port); shared by
    the PS-tier benches (BENCH_PS / BENCH_FAULT / BENCH_ELASTIC)."""
    import socket
    import subprocess
    import sys
    import tempfile

    from byteps_tpu.utils.hermetic import cpu_subprocess_env

    for _ in range(4):
        # The server binds root_port + 1 + server_id; only the data
        # port is ever bound here (no scheduler process), so probe THAT
        # one free and derive the root port from it.
        with socket.socket() as sk:
            sk.bind(("127.0.0.1", 0))
            port = sk.getsockname()[1]      # the server's data port
        env = cpu_subprocess_env({
            "DMLC_PS_ROOT_PORT": str(port - 1),
            "DMLC_NUM_WORKER": str(num_workers),
            "BYTEPS_SERVER_ENGINE_THREAD": str(engine_threads),
            **(extra_env or {}),
        })
        errf = tempfile.TemporaryFile(mode="w+")
        proc = subprocess.Popen(
            [sys.executable, "-m", "byteps_tpu.server"],
            env=env, stdout=subprocess.DEVNULL, stderr=errf)
        deadline = time.time() + 30
        while True:
            try:
                socket.create_connection(
                    ("127.0.0.1", port), 0.5).close()
                return proc, port
            except OSError:
                if proc.poll() is not None:
                    # Only an actual bind conflict is worth a retry on
                    # a fresh port; any other startup death (import
                    # error, missing native lib) must surface.
                    errf.seek(0)
                    stderr = errf.read()[-500:]
                    errf.close()
                    if "in use" not in stderr.lower():
                        raise RuntimeError(
                            f"PS server died at startup "
                            f"(rc={proc.returncode}): {stderr}")
                    break           # lost the port race — retry fresh
                if time.time() > deadline:
                    proc.kill()
                    proc.wait()
                    raise RuntimeError("PS server did not come up")
                time.sleep(0.1)
    raise RuntimeError("PS server lost the port race 4 times")


def bench_wire():
    """Raw-speed transport benchmark (BENCH_WIRE=1): the ≥85%-of-wire-
    floor acceptance number, measured by tools/wire_bench.py
    --echo-floor and recorded in the BENCH json rather than
    hand-calculated.

    value = `wire_pct_of_floor`: PS raw push_pull goodput (4 MiB
    partitions, interleaved best-of batches) as a percentage of the
    same host's raw socket echo floor on the same transport;
    vs_baseline = pct / 85 (the ROADMAP target).  BENCH_WIRE_UDS=1
    measures the AF_UNIX colocated fast path instead of loopback TCP.
    Host-only, like BENCH_PS.
    """
    import subprocess
    import sys

    from byteps_tpu.utils.hermetic import cpu_subprocess_env

    args = [sys.executable,
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "tools", "wire_bench.py"),
            "--echo-floor", "--json"]
    if os.environ.get("BENCH_WIRE_UDS", "0") == "1":
        args.append("--uds")
    if os.environ.get("BENCH_SMALL", "0") == "1":
        args.append("--quick")
    r = subprocess.run(args, env=cpu_subprocess_env({}),
                       capture_output=True, text=True, timeout=900)
    if r.returncode != 0:
        _error_record(f"wire bench failed rc={r.returncode}: "
                      f"{r.stderr[-400:]}")
        raise SystemExit(3)
    ef = json.loads(r.stdout)["echo_floor"]
    print(json.dumps({
        "metric": "wire_pct_of_floor",
        "value": ef["pct_of_floor"],
        "unit": "pct_of_echo_floor",
        "vs_baseline": round(ef["pct_of_floor"]
                             / ef["target_pct_of_floor"], 3),
        "detail": {**ef, **_note()},
    }))


def bench_fault():
    """Fault-tolerance benchmark: wall-clock cost of a mid-round
    connection reset through the chaos proxy (tools/chaos_proxy.py).

    value = `fault_reconnect_recovery_ms`: the extra time a push_pull
    round takes when its connection is RST mid-payload and the transport
    must park, re-dial, re-handshake, and replay — versus a healthy round
    (vs_baseline = faulted / healthy round time).  Measures the real
    client + real C++ server + real backoff path, loopback TCP.
    Host-only, like BENCH_PS.
    """
    import socket
    import subprocess
    import sys

    import numpy as np

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    from chaos_proxy import ChaosProxy

    from byteps_tpu.server.client import PSSession
    from byteps_tpu.utils.hermetic import cpu_subprocess_env


    backoff_ms = float(os.environ.get("BENCH_FAULT_BACKOFF_MS", "20"))
    reps = int(os.environ.get("BENCH_FAULT_REPS", "5"))
    proc, port = _boot_ps_server(engine_threads=2)
    proxy = ChaosProxy("127.0.0.1", port).start()
    try:
        sess = PSSession(["127.0.0.1"], [proxy.port], worker_id=0,
                         num_servers=1, wire_conns=1,
                         reconnect_attempts=8,
                         reconnect_backoff_ms=backoff_ms)
        x = np.random.default_rng(0).standard_normal(
            1 << 20, dtype=np.float32)            # 4 MB, one partition
        sess.push_pull(1, x)                      # init + warm
        healthy = []
        for _ in range(reps):
            t0 = time.perf_counter()
            sess.push_pull(1, x)
            healthy.append(time.perf_counter() - t0)
        faulted = []
        for _ in range(reps):
            proxy.reset_after(1 << 20)            # RST 1 MB into the push
            t0 = time.perf_counter()
            sess.push_pull(1, x)                  # parks, re-dials, replays
            faulted.append(time.perf_counter() - t0)
        stats = sess.transport_stats()
        sess.close()
        healthy_best = min(healthy)
        faulted_med = sorted(faulted)[len(faulted) // 2]
        recovery_ms = (faulted_med - healthy_best) * 1e3
        print(json.dumps({
            "metric": "fault_reconnect_recovery_ms",
            "value": round(recovery_ms, 1),
            "unit": "ms",
            "vs_baseline": round(faulted_med / healthy_best, 2),
            "detail": {
                "healthy_round_best_ms": round(healthy_best * 1e3, 1),
                "faulted_round_median_ms": round(faulted_med * 1e3, 1),
                "reps": reps,
                "reconnect_backoff_ms": backoff_ms,
                "reconnects": stats["reconnects"],
                "replayed_pushes": stats["replayed_pushes"],
                "replayed_pulls": stats["replayed_pulls"],
                "parked_total": stats["parked_total"],
                "fault": "RST 1 MiB into a 4 MiB push, one-shot, "
                         "via tools/chaos_proxy.py",
                "note": "value = median faulted round minus best healthy "
                        "round: park + backoff + re-dial + HELLO/INIT "
                        "re-handshake + replay",
                **_note(),
            },
        }))
    finally:
        proxy.stop()
        proc.kill()
        proc.wait()


def _boot_ring_servers(n: int, engine_threads: int = 2,
                       extra_env: dict = None):
    """Start `n` ring-armed PS servers on consecutive ports (the
    root+1+id convention both the servers' peer book and the workers
    derive).  Returns (procs, ports); retries the whole group on a port
    collision."""
    import socket
    import subprocess
    import sys

    from byteps_tpu.utils.hermetic import cpu_subprocess_env

    for _ in range(4):
        with socket.socket() as sk:
            sk.bind(("127.0.0.1", 0))
            base = sk.getsockname()[1]
        ports = [base + i for i in range(n)]
        procs = []
        ok = True
        for i in range(n):
            env = cpu_subprocess_env({
                "DMLC_PS_ROOT_PORT": str(base - 1),
                "DMLC_NUM_WORKER": "1",
                "DMLC_NUM_SERVER": str(n),
                "DMLC_SERVER_ID": str(i),
                "BYTEPS_TPU_RING": "1",
                "BYTEPS_SERVER_ENGINE_THREAD": str(engine_threads),
                **(extra_env or {}),
            })
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "byteps_tpu.server"], env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
        deadline = time.time() + 30
        up = set()
        while time.time() < deadline and len(up) < n:
            for i, p in enumerate(ports):
                if i in up:
                    continue
                try:
                    socket.create_connection(("127.0.0.1", p), 0.5).close()
                    up.add(i)
                except OSError:
                    if procs[i].poll() is not None:
                        ok = False
                        break
            if not ok:
                break
            time.sleep(0.1)
        if ok and len(up) == n:
            return procs, ports
        for p in procs:
            p.kill()
            p.wait()
    raise RuntimeError(f"could not boot {n} ring servers")


def bench_elastic():
    """Elastic-membership benchmark (BENCH_ELASTIC=1): wall-clock cost of
    the transitions an autoscaled/preempted fleet pays — both halves.

    Worker half (PR 7):
    `evict_detect_ms`: 2 workers mid-training with lease eviction armed
    (BYTEPS_TPU_EVICT_TIMEOUT_S = BENCH_ELASTIC_EVICT_S, default 0.5);
    worker 1 dies without notice, and the value is how long worker 0's
    next round blocks until the server evicts the corpse and re-finalizes
    the open round (minus a healthy round) — the unavailability window a
    permanent worker loss costs the survivors.

    `join_catchup_ms`: a replacement worker then HELLOs in while the
    survivor keeps stepping; the value is session construction -> its
    first completed push_pull (epoch admission + INIT round rebase +
    first post-join round).

    Server half (elastic PS ring):
    `migration_ms`: 2 ring-armed servers; server 1 is gracefully drained
    (bps-level drain_server: state handoff + redirect) and the value is
    the drain call plus the first post-drain round, minus a healthy
    round — the availability cost of scaling the PS tier down by one.

    `server_failover_ms`: 2 ring-armed servers with the worker-side
    server-lease scanner armed; server 1 is SIGKILLed mid-job and the
    value is how long the next round blocks until the scanner declares
    it dead, the survivors claim its key ranges, and the open round
    re-pushes — minus a healthy round.  Host-only, like BENCH_FAULT.
    """
    import threading

    import numpy as np

    from byteps_tpu.server.client import PSSession

    evict_s = float(os.environ.get("BENCH_ELASTIC_EVICT_S", "0.5"))
    proc, port = _boot_ps_server(
        engine_threads=2, num_workers=2,
        extra_env={"BYTEPS_TPU_EVICT_TIMEOUT_S": str(evict_s)})

    def mk(wid):
        return PSSession(["127.0.0.1"], [port], worker_id=wid,
                         num_servers=1, wire_conns=1,
                         evict_timeout_s=evict_s)

    try:
        s0, s1 = mk(0), mk(1)
        x = np.random.default_rng(0).standard_normal(
            1 << 18, dtype=np.float32)          # 1 MB, one partition
        for _ in range(3):                       # init + warm
            h0 = s0.push_pull_async(1, x)
            h1 = s1.push_pull_async(1, x)
            h0.wait(30); h1.wait(30)
        t0 = time.perf_counter()
        h0 = s0.push_pull_async(1, x)
        h1 = s1.push_pull_async(1, x)
        h0.wait(30); h1.wait(30)
        healthy_ms = (time.perf_counter() - t0) * 1e3

        # Permanent kill: worker 1 vanishes (no leave, no FIN courtesy).
        s1.close()
        t0 = time.perf_counter()
        s0.push_pull_async(1, x).wait(60)
        evict_detect_ms = (time.perf_counter() - t0) * 1e3 - healthy_ms

        # Replacement joins while the survivor keeps stepping.
        stop = threading.Event()

        def survivor():
            while not stop.is_set():
                try:
                    s0.push_pull_async(1, x).wait(60)
                except Exception:
                    return

        th = threading.Thread(target=survivor, daemon=True)
        th.start()
        t0 = time.perf_counter()
        s1b = mk(1)
        s1b.push_pull_async(1, x).wait(60)
        join_catchup_ms = (time.perf_counter() - t0) * 1e3
        stop.set()
        th.join(timeout=60)
        epoch = s0.membership()["epoch"]
        s0.close()
        s1b.close()
        detail = {
            "healthy_round_ms": round(healthy_ms, 1),
            "evict_timeout_s": evict_s,
            "final_epoch": epoch,
            "note": "evict_detect_ms = survivor's blocked round minus a "
                    "healthy round (lease expiry + re-finalize); "
                    "join_catchup_ms = session construction -> first "
                    "completed post-join push_pull",
            **_note(),
        }
        print(json.dumps({
            "metric": "evict_detect_ms",
            "value": round(evict_detect_ms, 1),
            "unit": "ms",
            "vs_baseline": round(evict_detect_ms / (evict_s * 1e3), 2),
            "detail": detail,
        }))
        print(json.dumps({
            "metric": "join_catchup_ms",
            "value": round(join_catchup_ms, 1),
            "unit": "ms",
            "vs_baseline": round(join_catchup_ms / max(healthy_ms, 1e-3),
                                 2),
            "detail": detail,
        }))
    finally:
        proc.kill()
        proc.wait()

    # ---- server half: graceful drain (migration) ------------------------
    import numpy as np
    from byteps_tpu.server.client import PSSession

    def ring_session(ports, srv_evict=0.0, audit=False):
        return PSSession(["127.0.0.1"] * len(ports), ports, worker_id=0,
                         num_servers=len(ports), wire_conns=1, ring=True,
                         server_evict_timeout_s=srv_evict, audit=audit,
                         partition_bytes=1 << 18)

    # Several 256 KiB keys so both servers own a share of the ring.
    keys = list(range(1, 9))
    x = np.random.default_rng(0).standard_normal(1 << 16,
                                                 dtype=np.float32)

    def round_all(sess, timeout=60):
        hs = [sess.push_pull_async(k, x) for k in keys]
        for h in hs:
            h.wait(timeout)

    procs, ports = _boot_ring_servers(2)
    plain_round_ms = None
    try:
        sess = ring_session(ports)
        for _ in range(3):                   # init + warm
            round_all(sess)
        t0 = time.perf_counter()
        round_all(sess)
        healthy_ms = (time.perf_counter() - t0) * 1e3
        plain_round_ms = healthy_ms          # replication-off baseline

        t0 = time.perf_counter()
        drain_doc = sess.drain_server(1)
        round_all(sess)                      # first fully re-homed round
        migration_ms = (time.perf_counter() - t0) * 1e3 - healthy_ms
        stats = sess.transport_stats()
        sess.close()
        print(json.dumps({
            "metric": "migration_ms",
            "value": round(migration_ms, 1),
            "unit": "ms",
            "vs_baseline": round(migration_ms / max(healthy_ms, 1e-3), 2),
            "detail": {
                "healthy_round_ms": round(healthy_ms, 1),
                "keys": len(keys),
                "ring_epoch": drain_doc.get("epoch"),
                "ring_redirects": stats.get("ring_redirects", 0),
                "note": "drain_server(1) (state handoff via CMD_MIGRATE "
                        "+ kMoved redirects) plus the first post-drain "
                        "round, minus a healthy round",
                **_note(),
            },
        }))
    finally:
        for p in procs:
            p.kill()
            p.wait()

    # ---- server half: failover (permanent server death) -----------------
    # Chain replication + the auditor are ARMED here (BYTEPS_TPU_REPL /
    # BYTEPS_TPU_AUDIT): the record proves the zero-loss law — the
    # SIGKILLed server's ranges resume from its ring successor's
    # replica, the audit cross-check counts the lost rounds (must be 0),
    # and the healthy-round delta vs the replication-off drain half
    # above prices what the protection costs on the publish path.
    procs, ports = _boot_ring_servers(
        2, extra_env={"BYTEPS_TPU_REPL": "1", "BYTEPS_TPU_AUDIT": "1"})
    os.environ["BYTEPS_TPU_REPL"] = "1"      # client-side reconcile law
    try:
        sess = ring_session(ports, srv_evict=evict_s, audit=True)
        for _ in range(3):
            round_all(sess)
        t0 = time.perf_counter()
        round_all(sess)
        healthy_ms = (time.perf_counter() - t0) * 1e3

        procs[1].kill()                      # the PS process is GONE
        procs[1].wait()
        t0 = time.perf_counter()
        round_all(sess, timeout=120)         # blocks until failover lands
        server_failover_ms = (time.perf_counter() - t0) * 1e3 - healthy_ms
        round_all(sess)                      # a clean post-failover round
        audit = sess.audit_check()
        lost_rounds = len(audit.get("lost_rounds") or ())
        stats = sess.transport_stats()
        srv = sess.server_stats()
        ring_epoch = sess.get_ring().get("epoch")
        sess.close()
        print(json.dumps({
            "metric": "server_failover_ms",
            "value": round(server_failover_ms, 1),
            "unit": "ms",
            "vs_baseline": round(server_failover_ms / (evict_s * 1e3), 2),
            "detail": {
                "healthy_round_ms": round(healthy_ms, 1),
                "server_evict_timeout_s": evict_s,
                "ring_epoch": ring_epoch,
                "server_failovers": stats.get("server_failovers", 0),
                "replayed_pushes": stats.get("replayed_pushes", 0),
                "repl_promotions": srv.get("repl_promotions", 0),
                "note": "SIGKILL of 1-of-2 ring servers with chain "
                        "replication armed; value = blocked round "
                        "(down-detect + ring epoch + replica adoption + "
                        "open-round re-push) minus a healthy round",
                **_note(),
            },
        }))
        print(json.dumps({
            "metric": "failover_lost_rounds",
            "value": lost_rounds,
            "unit": "rounds",
            "vs_baseline": 0.0,
            "detail": {
                "audit_mismatches": len(audit.get("mismatches") or ()),
                "audit_compared": audit.get("compared", 0),
                "repl_promotions": srv.get("repl_promotions", 0),
                "note": "audit cross-check after a SIGKILL failover "
                        "with BYTEPS_TPU_REPL=1 — the zero-loss law "
                        "says this is 0, always",
                **_note(),
            },
        }))
        if plain_round_ms:
            overhead_pct = (healthy_ms - plain_round_ms) \
                / max(plain_round_ms, 1e-3) * 100.0
            print(json.dumps({
                "metric": "repl_overhead_pct",
                "value": round(overhead_pct, 1),
                "unit": "pct",
                "vs_baseline": round(healthy_ms
                                     / max(plain_round_ms, 1e-3), 2),
                "detail": {
                    "repl_on_round_ms": round(healthy_ms, 1),
                    "repl_off_round_ms": round(plain_round_ms, 1),
                    "repl_bytes_total": srv.get("repl_bytes_total", 0),
                    "note": "healthy sync-round time with chain "
                            "replication armed vs off (same keys, same "
                            "tier) — the ack gate holds pulls for the "
                            "successor ack, so this prices the publish-"
                            "path cost of the zero-loss law",
                    **_note(),
                },
            }))
    finally:
        os.environ.pop("BYTEPS_TPU_REPL", None)
        for p in procs:
            p.kill()
            p.wait()


def bench_telemetry():
    """Telemetry-overhead benchmark: sync-round time with the metrics
    plane HOT (endpoint up + a scraper polling it + CMD_STATS refresh)
    vs OFF (BYTEPS_TPU_METRICS_PORT=0: no exporter, nothing scraping).

    The registry's per-partition feeds (push RTT / queue wait observes)
    are always on — they are lock-free and O(ns)-class, asserted by
    tests/test_telemetry.py — so the measurable cost of the telemetry
    subsystem is the export plane, and `telemetry_overhead_ms` is
    expected to sit within round-to-round noise.  Host-only, like
    BENCH_PS.  detail also reports the measured per-inc registry cost.
    """
    import threading
    import urllib.request

    import numpy as np

    from byteps_tpu.common import telemetry as tm
    from byteps_tpu.server.client import PSSession

    reps = int(os.environ.get("BENCH_TELEMETRY_REPS", "30"))
    proc, port = _boot_ps_server(engine_threads=2)
    try:
        sess = PSSession(["127.0.0.1"], [port], worker_id=0, num_servers=1)
        x = np.random.default_rng(0).standard_normal(
            1 << 20, dtype=np.float32)            # 4 MB, one partition
        sess.push_pull(1, x)                      # init + warm

        def rounds(n):
            times = []
            for _ in range(n):
                t0 = time.perf_counter()
                sess.push_pull(1, x)
                times.append(time.perf_counter() - t0)
            return times

        rounds(5)                                 # settle
        off = rounds(reps)                        # export plane off

        # _free_port is bind-then-close (TOCTOU): another process can take
        # the port before the exporter rebinds it — retry on a fresh one,
        # the same mitigation as _boot_ps_server.
        for attempt in range(4):
            try:
                exporter = tm.TelemetryExporter(
                    tm.get_registry(), port=_free_port(),
                    refresh=lambda: sess.server_stats()).start()
                break
            except OSError:
                if attempt == 3:
                    raise
        stop = threading.Event()

        def scrape():
            url = f"http://127.0.0.1:{exporter.port}/metrics"
            while not stop.is_set():
                try:
                    urllib.request.urlopen(url, timeout=2).read()
                except OSError:
                    pass
                stop.wait(0.05)                   # 20 scrapes/s: hostile

        scraper = threading.Thread(target=scrape, daemon=True)
        scraper.start()
        rounds(5)                                 # settle under scrape
        hot = rounds(reps)                        # export plane hot
        stop.set()
        scraper.join(timeout=5)
        exporter.stop()
        sess.close()

        # Per-inc registry cost, measured inline (the fast test asserts
        # the bound; this records the number alongside the round delta).
        c = tm.get_registry().counter("bench_telemetry_probe")
        n_inc = 200_000
        t0 = time.perf_counter()
        for _ in range(n_inc):
            c.inc()
        inc_ns = (time.perf_counter() - t0) / n_inc * 1e9

        off_med = sorted(off)[len(off) // 2]
        hot_med = sorted(hot)[len(hot) // 2]
        delta_ms = (hot_med - off_med) * 1e3
        print(json.dumps({
            "metric": "telemetry_overhead_ms",
            "value": round(delta_ms, 3),
            "unit": "ms",
            "vs_baseline": round(hot_med / off_med, 3),
            "detail": {
                "round_off_median_ms": round(off_med * 1e3, 2),
                "round_hot_median_ms": round(hot_med * 1e3, 2),
                "reps": reps,
                "scrape_hz": 20,
                "registry_inc_ns": round(inc_ns, 1),
                "note": "value = median 4MB sync round with the metrics "
                        "endpoint scraped at 20Hz (+CMD_STATS refresh "
                        "per scrape) minus median with the export plane "
                        "off; expected within round-to-round noise",
                **_note(),
            },
        }))
    finally:
        proc.kill()
        proc.wait()


def bench_audit():
    """Auditor-overhead benchmark (BENCH_AUDIT=1): sync-round time with
    the value-domain consistency auditor HOT (server publish digests +
    pull trailers + worker re-digest + health sampling every round) vs
    OFF (BYTEPS_TPU_AUDIT unset: the wire is byte-identical to
    pre-audit, asserted by tests/test_audit.py).

    `audit_overhead_ms` is the median per-round delta for a 4 MB
    partition; expected within round-to-round noise — the armed cost is
    one CRC pass over the published buffer per publish (server), one
    per pull (worker, off the receiver thread), and the trailer's loss
    of the zero-copy pull sink (one 4 MB body copy).  Host-only, like
    BENCH_PS; mirrors BENCH_TELEMETRY.
    """
    import numpy as np

    from byteps_tpu.server.client import PSSession

    reps = int(os.environ.get("BENCH_AUDIT_REPS", "30"))
    x = np.random.default_rng(0).standard_normal(
        1 << 20, dtype=np.float32)                # 4 MB, one partition

    def measure(audit: bool, health: int) -> tuple:
        extra = {"BYTEPS_TPU_AUDIT": "1"} if audit else {}
        proc, port = _boot_ps_server(engine_threads=2, extra_env=extra)
        try:
            sess = PSSession(["127.0.0.1"], [port], worker_id=0,
                             num_servers=1, audit=audit,
                             health_sample_rounds=health)
            sess.push_pull(1, x)                  # init + warm
            for _ in range(5):                    # settle
                sess.push_pull(1, x)
            times = []
            for _ in range(reps):
                t0 = time.perf_counter()
                sess.push_pull(1, x)
                times.append(time.perf_counter() - t0)
            checked = sess.audit_stats()["checked"] if audit else 0
            sess.close()
            return sorted(times)[len(times) // 2], checked
        finally:
            proc.kill()
            proc.wait()

    off_med, _ = measure(audit=False, health=0)
    hot_med, checked = measure(audit=True, health=0)
    health_med, _ = measure(audit=True, health=1)
    delta_ms = (hot_med - off_med) * 1e3
    print(json.dumps({
        "metric": "audit_overhead_ms",
        "value": round(delta_ms, 3),
        "unit": "ms",
        "vs_baseline": round(hot_med / off_med, 3),
        "detail": {
            "round_off_median_ms": round(off_med * 1e3, 2),
            "round_hot_median_ms": round(hot_med * 1e3, 2),
            "round_hot_health1_median_ms": round(health_med * 1e3, 2),
            "reps": reps,
            "audited_pulls": int(checked),
            "note": "value = median 4MB sync round with publish digests "
                    "+ pull trailers + worker re-digest (verify runs "
                    "off the critical path) minus median with the "
                    "auditor off; expected within round-to-round noise. "
                    "round_hot_health1 additionally samples gradient "
                    "health EVERY round (BYTEPS_TPU_HEALTH_SAMPLE_"
                    "ROUNDS=1, the max-hostile cadence)",
            **_note(),
        },
    }))


def bench_doctor():
    """Signal-plane overhead benchmark (BENCH_DOCTOR=1): sync-round time
    with the windowed key-signal plane + doctor rules HOT (window
    rolling every 0.5 s, per-part feeds live, CMD_STATS refresh per
    window, all 9 rules evaluated) vs OFF (BYTEPS_TPU_SIGNAL_WINDOW_S=0
    semantics: the module plane is None and every feed is a global
    read + None check).

    `signal_plane_overhead_ms` is the median per-round delta for a 4 MB
    partition, expected within round-to-round noise — the armed
    hot-path cost is one small dict update under a short lock per
    partition round trip; the per-window cost (one registry snapshot +
    rule pass, measured separately as `window_roll_ms`) runs on its own
    thread once per window.  Host-only, like BENCH_PS; mirrors
    BENCH_TELEMETRY.
    """
    import numpy as np

    from byteps_tpu.common import doctor as doctor_mod
    from byteps_tpu.common import signals
    from byteps_tpu.server.client import PSSession

    reps = int(os.environ.get("BENCH_DOCTOR_REPS", "30"))
    proc, port = _boot_ps_server(engine_threads=2)
    try:
        sess = PSSession(["127.0.0.1"], [port], worker_id=0,
                         num_servers=1)
        x = np.random.default_rng(0).standard_normal(
            1 << 20, dtype=np.float32)            # 4 MB, one partition
        sess.push_pull(1, x)                      # init + warm

        def rounds(n):
            times = []
            for _ in range(n):
                t0 = time.perf_counter()
                sess.push_pull(1, x)
                times.append(time.perf_counter() - t0)
            return times

        rounds(5)                                 # settle
        off = rounds(reps)                        # plane off (None)

        eng = doctor_mod.DoctorEngine()
        plane = signals.arm(
            window_s=0.5, history=32,
            refresh=lambda: sess.server_stats(),
            providers={"transport": sess.transport_stats},
            on_window=eng.observe)
        rounds(5)                                 # settle under windows
        hot = rounds(reps)                        # plane + doctor hot

        # Per-window roll cost over LOADED windows: the background
        # thread drains the accumulators every 0.5s, so stop it and
        # feed one round before each timed roll — timing back-to-back
        # rolls would fold empty windows and underreport exactly the
        # per-key work this number exists to quantify.
        signals.disarm()
        plane = signals.arm(
            window_s=60.0, history=32, start_thread=False,
            refresh=lambda: sess.server_stats(),
            providers={"transport": sess.transport_stats},
            on_window=eng.observe)
        rounds(1)
        keys_seen = len(plane.roll()["keys"])
        n_rolls = 10
        roll_total = 0.0
        for _ in range(n_rolls):
            rounds(1)                         # re-load the window
            t0 = time.perf_counter()
            plane.roll()
            roll_total += time.perf_counter() - t0
        roll_ms = roll_total / n_rolls * 1e3
        signals.disarm()
        sess.close()

        off_med = sorted(off)[len(off) // 2]
        hot_med = sorted(hot)[len(hot) // 2]
        delta_ms = (hot_med - off_med) * 1e3
        print(json.dumps({
            "metric": "signal_plane_overhead_ms",
            "value": round(delta_ms, 3),
            "unit": "ms",
            "vs_baseline": round(hot_med / off_med, 3),
            "detail": {
                "round_off_median_ms": round(off_med * 1e3, 2),
                "round_hot_median_ms": round(hot_med * 1e3, 2),
                "window_roll_ms": round(roll_ms, 3),
                "window_s": 0.5,
                "reps": reps,
                "keys_tracked": keys_seen,
                "note": "value = median 4MB sync round with the signal "
                        "plane rolling 0.5s windows + doctor rules + "
                        "CMD_STATS refresh per window minus median "
                        "with the plane off; expected within "
                        "round-to-round noise.  window_roll_ms is the "
                        "off-thread per-window cost (registry snapshot "
                        "+ classification + 9-rule pass)",
                **_note(),
            },
        }))
    finally:
        proc.kill()
        proc.wait()


def bench_fleet():
    """Fleet-plane benchmark (BENCH_FLEET=1): the two headline numbers
    the observability plane is accountable for.

    `fleet_plane_overhead_ms` — median 4 MB sync-round time with the
    fleet plane HOT (0.5 s signal windows each publishing one
    CMD_WINDOW frame and fetching the merged CMD_FLEET view — the full
    armed per-window wire cost) minus median with the plane idle (fleet
    wire armed, nothing published).  The publish/fetch pair rides the
    window-roll thread, so the delta is expected within round-to-round
    noise — the armed-cost-off-critical-path law this bench exists to
    keep honest.  Lower is better.

    `fleet_goodput_pct` — the goodput ledger's compute share over the
    live merged view's last aligned window: wall-time partitioned
    EXACTLY into compute/wire/straggler-wait/stall/recovery/disruption
    (the partition is asserted inside the ledger).  Higher is better.
    Host-only, like BENCH_PS; mirrors BENCH_DOCTOR's shape.
    """
    import numpy as np

    from byteps_tpu.common import doctor as doctor_mod
    from byteps_tpu.common import goodput as goodput_mod
    from byteps_tpu.common import signals
    from byteps_tpu.server.client import PSSession

    reps = int(os.environ.get("BENCH_FLEET_REPS", "30"))
    proc, port = _boot_ps_server(engine_threads=2,
                                 extra_env={"BYTEPS_TPU_FLEET": "1"})
    try:
        sess = PSSession(["127.0.0.1"], [port], worker_id=0,
                         num_servers=1, fleet=True)
        if not sess._fleet_wire:
            raise RuntimeError("fleet bootstrap probe downgraded against "
                               "a fleet-armed server — wire bug")
        x = np.random.default_rng(0).standard_normal(
            1 << 20, dtype=np.float32)            # 4 MB, one partition
        sess.push_pull(1, x)                      # init + warm

        def rounds(n):
            times = []
            for _ in range(n):
                t0 = time.perf_counter()
                sess.push_pull(1, x)
                times.append(time.perf_counter() - t0)
            return times

        rounds(5)                                 # settle
        off = rounds(reps)                        # armed wire, idle plane

        published = {"n": 0}

        def _on_window(summary):
            doc = doctor_mod.fleet_publish_doc(
                summary, 0, clock=sess.fleet_clock_offset())
            if sess.publish_window(int(doc.get("window") or 0), doc):
                published["n"] += 1
            sess.fetch_fleet()

        signals.arm(window_s=0.5, history=32,
                    refresh=lambda: sess.server_stats(),
                    providers={"transport": sess.transport_stats},
                    on_window=_on_window)
        rounds(5)                                 # settle under windows
        hot = rounds(reps)                        # publish+fetch per window
        time.sleep(0.7)                           # let the last window roll
        view = sess.fetch_fleet()
        fw = doctor_mod.fleet_windows_from_view(view)
        signals.disarm()
        sess.close()
        if not fw:
            raise RuntimeError("no fleet window published over the run")
        ledger = goodput_mod.fleet_ledger(fw[-1])

        off_med = sorted(off)[len(off) // 2]
        hot_med = sorted(hot)[len(hot) // 2]
        delta_ms = (hot_med - off_med) * 1e3
        print(json.dumps({
            "metric": "fleet_goodput_pct",
            "value": round(ledger["goodput_pct"], 2),
            "unit": "pct",
            "detail": {
                "window": ledger["window"],
                "total_s": round(ledger["total_s"], 3),
                "seconds": {c: round(v, 4)
                            for c, v in ledger["seconds"].items()},
                "windows_published": published["n"],
                "note": "compute share of fleet wall-time from the "
                        "goodput ledger over the live merged CMD_FLEET "
                        "view's last aligned window; the six categories "
                        "sum exactly to the total (asserted)",
                **_note(),
            },
        }))
        print(json.dumps({
            "metric": "fleet_plane_overhead_ms",
            "value": round(delta_ms, 3),
            "unit": "ms",
            "vs_baseline": round(hot_med / off_med, 3),
            "detail": {
                "round_off_median_ms": round(off_med * 1e3, 2),
                "round_hot_median_ms": round(hot_med * 1e3, 2),
                "window_s": 0.5,
                "reps": reps,
                "windows_published": published["n"],
                "note": "value = median 4MB sync round with one "
                        "CMD_WINDOW publish + CMD_FLEET fetch per 0.5s "
                        "window minus median with the plane idle; the "
                        "pair rides the window-roll thread, so expected "
                        "within round-to-round noise",
                **_note(),
            },
        }))
    finally:
        proc.kill()
        proc.wait()


def bench_autotune():
    """Adaptive-compression benchmark (BENCH_AUTOTUNE=1): how close the
    self-tuning control loop gets an UNTUNED job to the HAND-TUNED
    config's step time — the ISSUE-13 headline.

    Workload: two 2 MB gradient keys + one 16 KiB bias key, synchronous
    push_pull rounds against the real native server over loopback.
    HAND-TUNED registers the expert config up front (onebit+EF on the
    big keys, the bias raw — what the class->action table in
    docs/gradient-compression.md prescribes for this shape).  UNTUNED
    starts everything raw with the tuner armed (0.4 s signal windows,
    hold=1): the tuner must discover the same assignment live through
    CMD_CODEC renegotiations, and the measured steady-state step time
    is compared.  `autotune_step_time_gap_pct` = (untuned_with_tuner -
    hand_tuned) / hand_tuned * 100; lower is better, 0 = converged.
    Per-key final codec assignments and tuner_switches_total ride the
    detail.  Host-only (no device backend), honest about the 2-core
    container: on a CPU-bound loopback the compressed and raw configs
    can land within noise, in which case the gap is honest noise around
    0 — the number being measured is the TUNER's convergence, not the
    codec's win.
    """
    import numpy as np

    from byteps_tpu.common import signals
    from byteps_tpu.common.tuner import Tuner
    from byteps_tpu.server.client import PSSession

    reps = int(os.environ.get("BENCH_AUTOTUNE_REPS", "40"))
    warm_s = float(os.environ.get("BENCH_AUTOTUNE_WARM_S", "4.0"))
    proc, port = _boot_ps_server(engine_threads=2)
    rng = np.random.default_rng(0)
    big_a = rng.standard_normal(1 << 19, dtype=np.float32)   # 2 MB
    big_b = rng.standard_normal(1 << 19, dtype=np.float32)   # 2 MB
    bias = rng.standard_normal(1 << 12, dtype=np.float32)    # 16 KiB

    def step(sess):
        hs = [sess.push_pull_async(1, big_a),
              sess.push_pull_async(2, big_b),
              sess.push_pull_async(3, bias)]
        for h in hs:
            h.wait()

    def timed_steps(sess, n):
        times = []
        for _ in range(n):
            t0 = time.perf_counter()
            step(sess)
            times.append(time.perf_counter() - t0)
        return sorted(times)[len(times) // 2]

    try:
        # --- hand-tuned: the expert assignment, fixed up front --------
        sess = PSSession(["127.0.0.1"], [port], worker_id=0,
                         num_servers=1)
        sess.register_compressor(1, {"compressor": "onebit",
                                     "ef": "vanilla"})
        sess.register_compressor(2, {"compressor": "onebit",
                                     "ef": "vanilla"})
        for _ in range(8):
            step(sess)                              # settle
        hand_med = timed_steps(sess, reps)
        sess.close()

        # --- untuned + tuner: starts raw, converges live --------------
        sess = PSSession(["127.0.0.1"], [port], worker_id=0,
                         num_servers=1)
        tuner = Tuner(sess, propose=True, hold=1, blacklist=4,
                      margin_rounds=2)
        plane = signals.arm(window_s=0.4, history=32,
                            on_window=tuner.observe)
        deadline = time.time() + warm_s
        warm_steps = 0
        while time.time() < deadline:
            step(sess)                              # tuner converges here
            warm_steps += 1
        tuned_med = timed_steps(sess, reps)
        signals.disarm()
        final = {k: v["name"] for k, v in sess.codec_table().items()}
        tstate = tuner.state()
        stale = sess.transport_stats()["codec_stale_retries"]
        sess.close()

        gap_pct = (tuned_med - hand_med) / hand_med * 100.0
        print(json.dumps({
            "metric": "autotune_step_time_gap_pct",
            "value": round(gap_pct, 2),
            "unit": "pct_gap",
            "vs_baseline": round(tuned_med / hand_med, 3),
            "detail": {
                "hand_tuned_step_ms": round(hand_med * 1e3, 3),
                "untuned_with_tuner_step_ms": round(tuned_med * 1e3, 3),
                "tuner_switches_total": tstate["switches_total"],
                "tuner_reverts_total": tstate["reverts_total"],
                "codec_stale_retries": stale,
                "final_codecs": final,
                "warm_steps": warm_steps,
                "reps": reps,
                "note": "value = (untuned-with-tuner - hand-tuned) / "
                        "hand-tuned step time in %, medians over "
                        f"{reps} steps after {warm_s:.0f}s of live "
                        "convergence; 0 = the tuner found the expert "
                        "config.  Loopback on a small host can put "
                        "both configs within noise — the number "
                        "measures tuner convergence, not codec wins",
                **_note(),
            },
        }))
    finally:
        proc.kill()
        proc.wait()


_KNOB_WORKER_CODE = """
import json, os, time
import numpy as np
import jax.numpy as jnp
import byteps_tpu as bps

reps = int(os.environ["KB_REPS"])
warm_s = float(os.environ["KB_WARM_S"])
expert = os.environ.get("KB_EXPERT", "0") == "1"
bps.init()
rng = np.random.default_rng(0)
tree = {}
# Two FC-sized gradients + a sheaf of layernorm-sized leaves: the
# mixed shape both the fusion planner and the codec dial care about.
tree["fc1.w"] = jnp.asarray(rng.standard_normal(1 << 19).astype(np.float32))
tree["fc2.w"] = jnp.asarray(rng.standard_normal(1 << 19).astype(np.float32))
for i in range(48):
    tree[f"ln{i:02d}.g"] = jnp.asarray(
        rng.standard_normal(1 << 10).astype(np.float32))
names = sorted(tree)
if expert:
    bps.register_compressor("fc1.w", {"compressor": "onebit",
                                      "ef": "vanilla"})
    bps.register_compressor("fc2.w", {"compressor": "onebit",
                                      "ef": "vanilla"})

def step():
    out = bps.push_pull_tree(tree, name="knobwl", average=False,
                             leaf_names=names)
    jnp.asarray(out["fc1.w"]).block_until_ready()

deadline = time.time() + warm_s
warm_steps = 0
while time.time() < deadline or warm_steps < 8:
    step()
    warm_steps += 1
times = []
for _ in range(reps):
    t0 = time.perf_counter()
    step()
    times.append(time.perf_counter() - t0)
med = sorted(times)[len(times) // 2]
tstate = {}
try:
    tstate = bps.get_tuner() or {}
except Exception:
    pass
print("KB_RESULT " + json.dumps({
    "step_ms": med * 1e3,
    "warm_steps": warm_steps,
    "knob_table": tstate.get("knob_table"),
    "predict_jumps_total": tstate.get("predict_jumps_total", 0),
    "switches_total": tstate.get("switches_total", 0),
    "cost_model": tstate.get("cost_model"),
    "final_codecs": {k: v.get("codec")
                     for k, v in (tstate.get("keys") or {}).items()},
}))
bps.shutdown()
"""


def bench_knob():
    """Knob-plane benchmark (BENCH_KNOB=1): a cold-start job whose
    predictive tuner must DISCOVER the global knobs live vs the same
    workload hand-tuned by an expert up front — the CMD_KNOB headline.

    Both arms launch the same mixed-key workload (two 2 MB FC gradients
    + 48 layernorm-sized 4 KiB leaves through push_pull_tree) with a
    deliberately naive launch config (64 KiB fusion buckets, raw
    codecs).  EXPERT overrides up front: 256 KiB fusion (one bucket
    holds the whole layernorm sheaf) and onebit+EF on the FC keys.
    COLD keeps the naive launch but arms the tuner with a persisted
    codec cost model (seeded here by an in-tree
    ``wire_bench --codec-sweep --quick --json`` run): it must
    predict-jump the FC codecs from the model and actuate
    FUSION_BYTES doublings through epoch-versioned CMD_KNOB sets at
    round boundaries, mid-job, no restart.
    ``knob_step_time_gap_pct`` = (cold - expert) / expert * 100; <= 0
    means the cold-start tuner matched or beat the expert.  The
    cost-model seed and COLD's final knob assignments ride the detail.
    Host-only loopback on a small container: both arms can land within
    noise (same honesty clause as BENCH_AUTOTUNE) — the number
    measures knob-plane convergence, not the knobs' absolute win.
    """
    import subprocess
    import sys
    import tempfile

    from byteps_tpu.utils.hermetic import cpu_subprocess_env

    reps = int(os.environ.get("BENCH_KNOB_REPS", "30"))
    warm_s = float(os.environ.get("BENCH_KNOB_WARM_S", "8.0"))

    # Seed the cost model at a bench-private path — never the operator's
    # real ~/.cache table.
    tmpdir = tempfile.mkdtemp(prefix="bench_knob_")
    model_path = os.path.join(tmpdir, "codec_cost_model.json")
    sweep = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tools", "wire_bench.py"),
         "--codec-sweep", "--quick", "--json"],
        env=cpu_subprocess_env(
            {"BYTEPS_TPU_KNOB_COST_MODEL": model_path}),
        capture_output=True, text=True, timeout=600)
    if sweep.returncode != 0 or not os.path.exists(model_path):
        raise RuntimeError(f"cost-model seed sweep failed: "
                           f"{sweep.stderr[-500:]}")
    with open(model_path) as f:
        model_rows = len(json.load(f).get("codec_sweep") or [])

    def run_arm(extra_env: dict) -> dict:
        proc, port = _boot_ps_server(engine_threads=2)
        try:
            env = cpu_subprocess_env({
                "BYTEPS_TPU_PS_MODE": "1",
                "DMLC_NUM_WORKER": "1",
                "DMLC_NUM_SERVER": "1",
                "DMLC_PS_ROOT_PORT": str(port - 1),
                # The naive launch config both arms start from.
                "BYTEPS_TPU_FUSION_BYTES": str(64 << 10),
                "KB_REPS": str(reps),
                "KB_WARM_S": str(warm_s),
                **extra_env,
            })
            r = subprocess.run([sys.executable, "-c", _KNOB_WORKER_CODE],
                               env=env, capture_output=True, text=True,
                               timeout=900)
            if r.returncode != 0:
                raise RuntimeError(f"knob bench arm failed: "
                                   f"{r.stderr[-1500:]}")
            for line in r.stdout.splitlines():
                if line.startswith("KB_RESULT "):
                    return json.loads(line[len("KB_RESULT "):])
            raise RuntimeError(f"knob bench arm emitted no result: "
                               f"{r.stdout[-500:]}")
        finally:
            proc.kill()
            proc.wait()

    expert = run_arm({"KB_EXPERT": "1",
                      "BYTEPS_TPU_FUSION_BYTES": str(256 << 10)})
    cold = run_arm({"BYTEPS_TPU_TUNER": "1",
                    "BYTEPS_TPU_SIGNAL_WINDOW_S": "0.4",
                    "BYTEPS_TPU_TUNER_HOLD": "1",
                    "BYTEPS_TPU_KNOB_ACTUATE": "1",
                    "BYTEPS_TPU_KNOB_COST_MODEL": model_path})

    gap_pct = ((cold["step_ms"] - expert["step_ms"])
               / expert["step_ms"] * 100.0)
    print(json.dumps({
        "metric": "knob_step_time_gap_pct",
        "value": round(gap_pct, 2),
        "unit": "pct_gap",
        "vs_baseline": round(cold["step_ms"] / expert["step_ms"], 3),
        "detail": {
            "expert_step_ms": round(expert["step_ms"], 3),
            "cold_with_tuner_step_ms": round(cold["step_ms"], 3),
            "cost_model_path": model_path,
            "cost_model_rows": model_rows,
            "predict_jumps_total": cold.get("predict_jumps_total", 0),
            "tuner_switches_total": cold.get("switches_total", 0),
            "final_knob_table": cold.get("knob_table"),
            "final_codecs": cold.get("final_codecs"),
            "launch_fusion_bytes": 64 << 10,
            "expert_fusion_bytes": 256 << 10,
            "warm_steps": cold.get("warm_steps"),
            "reps": reps,
            "note": "value = (cold-start-with-predictive-tuner - "
                    "hand-tuned expert) / expert step time in %, "
                    f"medians over {reps} steps after {warm_s:.0f}s of "
                    "live convergence; <= 0 = the knob plane found the "
                    "expert config mid-job.  Loopback on a small host "
                    "can put both arms within noise — the number "
                    "measures knob-plane convergence, not the knobs' "
                    "absolute win",
            **_note(),
        },
    }))


def bench_hier():
    """Hierarchical-reduction benchmark (BENCH_HIER=1): the ISSUE-15
    headline — the same 4-worker synchronous workload run FLAT (every
    chip pushes/pulls the full gradient) and HIERARCHICAL (2 slices x 2
    chips: in-graph psum intra-slice, one leader per slice on the wire,
    broadcast back), against the real native server over loopback.

    Headline ``hier_wire_bytes_saved_pct`` = (1 - hier_bytes /
    flat_bytes) * 100 — structurally ~(1 - 1/S) for slice size S, read
    from the transport lane counters (payload bytes actually sent), with
    the step-time delta in the detail.  Host-only honesty: on a small
    loopback container the in-graph psum and the wire round trip share
    cores, so step time can land anywhere within noise — the number
    being measured is the wire traffic removed, which is what DCN-bound
    pods buy with this mode.
    """
    import threading

    import numpy as np

    from byteps_tpu.parallel.hierarchy import (HierarchicalReducer,
                                               reset_slice_groups)
    from byteps_tpu.server.client import PSSession

    reps = int(os.environ.get("BENCH_HIER_REPS", "30"))
    slice_size = max(1, int(os.environ.get("BENCH_HIER_SLICE", "2")))
    world = 4
    n = 1 << 18                       # 1 MiB f32 per worker per round
    rng = np.random.default_rng(0)
    grads = [rng.standard_normal(n).astype(np.float32)
             for _ in range(world)]

    def run(hier: bool) -> dict:
        reset_slice_groups()
        extra = ({"BYTEPS_TPU_SLICE_SIZE": str(slice_size)}
                 if hier else None)
        proc, port = _boot_ps_server(engine_threads=2, num_workers=world,
                                     extra_env=extra)
        try:
            sessions = [PSSession(["127.0.0.1"], [port], worker_id=w,
                                  num_servers=1, wire_conns=1,
                                  slice_size=slice_size if hier else 1)
                        for w in range(world)]
            reducers = ([HierarchicalReducer(s, w, slice_size,
                                             world=world)
                         for w, s in enumerate(sessions)]
                        if hier else None)
            times = []

            def worker(w, barrier):
                for r in range(reps + 3):
                    barrier.wait()
                    t0 = time.perf_counter()
                    if hier:
                        reducers[w].push_pull_flat(1, grads[w])
                    else:
                        sessions[w].push_pull_async(
                            1, grads[w]).wait(60)
                    if w == 0 and r >= 3:          # settle 3 rounds
                        times.append(time.perf_counter() - t0)

            barrier = threading.Barrier(world)
            ts = [threading.Thread(target=worker, args=(w, barrier))
                  for w in range(world)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=300)
            if any(t.is_alive() for t in ts):
                raise RuntimeError("bench worker hung")
            per_worker = [s.transport_stats()["lane_bytes_total"]
                          for s in sessions]
            for s in sessions:
                s.close()
            return {"step_ms": sorted(times)[len(times) // 2] * 1e3,
                    "bytes_per_worker": per_worker,
                    "bytes_total": int(sum(per_worker))}
        finally:
            proc.kill()
            proc.wait()

    flat = run(False)
    hier = run(True)
    saved_pct = (1.0 - hier["bytes_total"] / flat["bytes_total"]) * 100.0
    print(json.dumps({
        "metric": "hier_wire_bytes_saved_pct",
        "value": round(saved_pct, 2),
        "unit": "pct",
        "detail": {
            "slice_size": slice_size,
            "workers": world,
            "flat_bytes_total": flat["bytes_total"],
            "hier_bytes_total": hier["bytes_total"],
            "flat_bytes_per_worker": flat["bytes_per_worker"],
            "hier_bytes_per_worker": hier["bytes_per_worker"],
            "flat_step_ms": round(flat["step_ms"], 3),
            "hier_step_ms": round(hier["step_ms"], 3),
            "step_time_delta_pct": round(
                (hier["step_ms"] - flat["step_ms"])
                / flat["step_ms"] * 100.0, 2),
            "reps": reps,
            "note": "value = wire payload bytes removed by leaders-only "
                    "push_pull, ~(1 - 1/slice_size) by construction; "
                    "step-time delta on a loopback container shares "
                    "cores between the psum and the wire and is "
                    "reported as detail, not headline",
            **_note(),
        },
    }))


def bench_serveropt():
    """Server-resident-optimizer benchmark (BENCH_SERVEROPT=1): step
    time and per-worker optimizer-state bytes, server-side update stage
    vs the worker-local optax baseline, on the same workload — the
    ISSUE-14 headline.

    Workload: one ~4.2 MB flat Adam-trained parameter vector (two 2 MB
    "layers" + a 16 KiB bias, flattened — the BENCH_AUTOTUNE key mix),
    synchronous rounds against the real native server over loopback.
    LOCAL pulls the gradient sum and runs optax here (N workers would
    each hold the full m/v slots and run the identical step N times);
    SERVER pushes the same gradients and pulls post-update parameters
    (CMD_OPT — the slots live in the server's KeyState, once).
    `serveropt_step_time_gap_pct` = (server - local) / local * 100;
    lower is better, and the structural win is in the detail:
    `worker_opt_state_bytes` collapses to 0 in server mode while
    `server_opt_slot_bytes` picks the state up exactly once, and
    `param_version` == rounds proves exactly-one update.  Host-only
    honesty: on a 2-core loopback container the wire round trip
    dominates and the eliminated local optax pass can land within
    noise — the number being measured is the redundancy moved, not a
    loopback speedup.
    """
    import numpy as np

    from byteps_tpu.parallel.server_opt import ServerOptTrainer
    from byteps_tpu.server.client import PSSession

    reps = int(os.environ.get("BENCH_SERVEROPT_REPS", "30"))
    rng = np.random.default_rng(0)
    params = {"layer_a": rng.standard_normal(1 << 19, dtype=np.float32),
              "layer_b": rng.standard_normal(1 << 19, dtype=np.float32),
              "bias": rng.standard_normal(1 << 12, dtype=np.float32)}
    grads = {k: rng.standard_normal(v.shape, dtype=np.float32)
             for k, v in params.items()}
    kw = {"opt": "adam", "lr": 1e-3}

    results = {}
    for mode in ("local", "server"):
        proc, port = _boot_ps_server(engine_threads=2)
        try:
            sess = PSSession(["127.0.0.1"], [port], worker_id=0,
                             num_servers=1)
            tr = ServerOptTrainer(sess, params, kw,
                                  name=f"bench_{mode}", mode=mode)
            for _ in range(6):
                tr.step(grads)                      # settle
            times = []
            for _ in range(reps):
                t0 = time.perf_counter()
                tr.step(grads)
                times.append(time.perf_counter() - t0)
            med = sorted(times)[len(times) // 2]
            st = sess.server_stats()
            results[mode] = {
                "step_ms": med * 1e3,
                "worker_opt_state_bytes": tr.opt_state_bytes(),
                "server_opt_slot_bytes": int(st.get("opt_slot_bytes",
                                                    0)),
                "opt_updates": int(st.get("opt_updates", 0)),
                "rounds": tr.rounds,
                "param_version": max(
                    [int(d.get("param_version", 0))
                     for d in tr.server_docs().values()] or [0]),
            }
            sess.close()
        finally:
            proc.kill()
            proc.wait()

    loc, srv = results["local"], results["server"]
    gap_pct = (srv["step_ms"] - loc["step_ms"]) / loc["step_ms"] * 100.0
    print(json.dumps({
        "metric": "serveropt_step_time_gap_pct",
        "value": round(gap_pct, 2),
        "unit": "pct_gap",
        "vs_baseline": round(srv["step_ms"] / loc["step_ms"], 3),
        "detail": {
            "local_step_ms": round(loc["step_ms"], 3),
            "server_step_ms": round(srv["step_ms"], 3),
            "local_worker_opt_state_bytes":
                loc["worker_opt_state_bytes"],
            "server_worker_opt_state_bytes":
                srv["worker_opt_state_bytes"],
            "server_opt_slot_bytes": srv["server_opt_slot_bytes"],
            "server_param_version": srv["param_version"],
            "server_rounds": srv["rounds"],
            "reps": reps,
            "note": "value = (server-resident - worker-local) / "
                    "worker-local Adam step time in %; the structural "
                    "claim is worker_opt_state_bytes -> 0 in server "
                    "mode (slots live once, server-side) and "
                    "param_version == rounds (exactly-one update). "
                    "Loopback on a small host can put both within "
                    "noise — the redundancy moved is the headline",
            **_note(),
        },
    }))


def bench_sparse():
    """Row-sparse embedding benchmark (BENCH_SPARSE=1): the PS tier as a
    recommendation-scale lookup tier — the ISSUE-17 headline.

    Workload: a server-resident rows x width f32 embedding table armed
    with row-wise Adagrad, driven by a zipfian id stream (the recsys
    shape: a small hot set absorbs most lookups).  Phase 1 trains
    sparse rounds (push (indices, rows), server steps exactly the
    touched rows, pull the post-update rows).  Phase 2 is the serving
    path: batched ungated row reads through the param_version-keyed
    hot-row LRU cache, where a warm zipf head costs ZERO wire frames.

    Headline `sparse_lookup_rows_per_s` = rows served per second over
    the read phase (higher is better); the structural numbers ride in
    the detail: `cache_hit_rate` (zipf head absorbed client-side),
    `p99_pull_ms` (tail of a batched read), and the wire-economy ratio
    `touched_frac` — the fraction of the table a training round
    actually shipped (dense push_pull would ship 1.0 every round).
    """
    import numpy as np

    from byteps_tpu.parallel.embedding import EmbeddingTable
    from byteps_tpu.server.client import PSSession

    rows = int(os.environ.get("BENCH_SPARSE_ROWS", "200000"))
    width = int(os.environ.get("BENCH_SPARSE_WIDTH", "64"))
    batch = int(os.environ.get("BENCH_SPARSE_BATCH", "4096"))
    rounds = int(os.environ.get("BENCH_SPARSE_ROUNDS", "15"))
    reads = int(os.environ.get("BENCH_SPARSE_READS", "60"))
    rng = np.random.default_rng(0)

    def zipf_ids(n):
        # rank-based zipfian over [0, rows): rejection-free fold of the
        # unbounded zipf draw onto the table (head stays the head).
        return (rng.zipf(1.2, n).astype(np.int64) - 1) % rows

    proc, port = _boot_ps_server(engine_threads=2)
    try:
        sess = PSSession(["127.0.0.1"], [port], worker_id=0,
                         num_servers=1)
        table = EmbeddingTable(
            sess, rows=rows, width=width, name="bench_emb",
            opt_kwargs={"opt": "adagrad", "lr": 0.05},
            init=lambda srows, w, s: np.zeros((srows, w), np.float32))

        touched = set()
        t0 = time.perf_counter()
        for _ in range(rounds):
            ids = zipf_ids(batch)
            touched.update(np.unique(ids).tolist())
            g = rng.standard_normal((batch, width)).astype(np.float32)
            table.push_pull(ids, g)
        train_s = time.perf_counter() - t0

        read_batches = [zipf_ids(batch) for _ in range(reads)]
        table.lookup(read_batches[0])               # settle / warm
        times = []
        t0 = time.perf_counter()
        for ids in read_batches:
            t1 = time.perf_counter()
            table.lookup(ids)
            times.append(time.perf_counter() - t1)
        read_s = time.perf_counter() - t0

        cs = sess.embed_cache_stats()
        st = sess.server_stats()
        sess.close()
    finally:
        proc.kill()
        proc.wait()

    total_read_rows = batch * len(read_batches)
    rows_per_s = total_read_rows / read_s
    hits, misses = cs.get("hits", 0), cs.get("misses", 0)
    hit_rate = hits / max(1, hits + misses)
    times.sort()
    p99_ms = times[min(len(times) - 1, int(0.99 * len(times)))] * 1e3
    print(json.dumps({
        "metric": "sparse_lookup_rows_per_s",
        "value": round(rows_per_s, 1),
        "unit": "rows_per_s",
        "detail": {
            "rows": rows, "width": width, "batch": batch,
            "train_rounds": rounds, "read_batches": reads,
            "cache_hit_rate": round(hit_rate, 4),
            "cache_hits": int(hits), "cache_misses": int(misses),
            "rows_cached": int(cs.get("rows_cached", 0)),
            "p99_pull_ms": round(p99_ms, 3),
            "p50_pull_ms": round(times[len(times) // 2] * 1e3, 3),
            "train_round_ms": round(train_s / max(1, rounds) * 1e3, 3),
            "touched_frac": round(len(touched) / rows, 4),
            "server_rows_served": int(st.get("embed_rows_served", 0)),
            "server_table_bytes": int(st.get("embed_table_bytes", 0)),
            "note": "value = rows served per second over the zipfian "
                    "read phase; the structural claims are "
                    "cache_hit_rate (the zipf head served with zero "
                    "wire frames) and touched_frac (a training round "
                    "ships that fraction of the table — dense "
                    "push_pull ships 1.0)",
            **_note(),
        },
    }))


def bench_trace():
    """Tracing-overhead benchmark: sync-round time with the distributed
    tracer HOT (worker span recording + traced wire flags + server-side
    span ring + clock sync) vs OFF (BYTEPS_TRACE_ON unset: untraced
    frames are byte-identical to the pre-trace wire, asserted by
    tests/test_trace.py).

    `trace_overhead_ms` is the median per-round delta; expected within
    round-to-round noise — the tracer's hot-path cost is a few clock
    reads and a mutex-guarded ring append per partition per stage.
    Host-only, like BENCH_PS; mirrors BENCH_TELEMETRY.
    """
    import tempfile

    import numpy as np

    from byteps_tpu.core.native import get_core
    from byteps_tpu.server.client import PSSession

    reps = int(os.environ.get("BENCH_TRACE_REPS", "30"))
    proc, port = _boot_ps_server(engine_threads=2)
    core = get_core()
    try:
        sess = PSSession(["127.0.0.1"], [port], worker_id=0, num_servers=1)
        x = np.random.default_rng(0).standard_normal(
            1 << 20, dtype=np.float32)            # 4 MB, one partition

        def rounds(n):
            times = []
            for _ in range(n):
                t0 = time.perf_counter()
                sess.push_pull(1, x)
                times.append(time.perf_counter() - t0)
            return times

        sess.push_pull(1, x)                      # init + warm
        rounds(5)                                 # settle
        off = rounds(reps)                        # tracer off

        core.trace_enable(True)
        sess.sync_clocks()                        # the trace-enable leg
        rounds(5)                                 # settle traced
        hot = rounds(reps)                        # tracer hot
        worker_spans = core.trace_count()
        server_spans = sess.fetch_server_trace()
        core.trace_enable(False)
        # Drain the worker buffer so a later bench in the same process
        # never inherits this one's spans.
        core.trace_dump(os.path.join(tempfile.gettempdir(),
                                     "bps_bench_trace.json"), 0)
        sess.close()

        off_med = sorted(off)[len(off) // 2]
        hot_med = sorted(hot)[len(hot) // 2]
        delta_ms = (hot_med - off_med) * 1e3
        print(json.dumps({
            "metric": "trace_overhead_ms",
            "value": round(delta_ms, 3),
            "unit": "ms",
            "vs_baseline": round(hot_med / off_med, 3),
            "detail": {
                "round_off_median_ms": round(off_med * 1e3, 2),
                "round_hot_median_ms": round(hot_med * 1e3, 2),
                "reps": reps,
                "worker_spans": int(worker_spans),
                "server_spans": len(server_spans),
                "server_stages": sorted(
                    {s["stage"] for s in server_spans}),
                "note": "value = median 4MB sync round with worker+server "
                        "span recording on (traced wire flags, server "
                        "ring appends) minus median with tracing off; "
                        "expected within round-to-round noise",
                **_note(),
            },
        }))
    finally:
        proc.kill()
        proc.wait()


def _free_port() -> int:
    import socket
    with socket.socket() as sk:
        sk.bind(("127.0.0.1", 0))
        return sk.getsockname()[1]


def bench_ps():
    """PS-tier wire benchmark: push_pull goodput through the real native
    KV server over loopback TCP.

    The reference's only automated perf check is the ps-lite transport
    benchmark its CI runs (reference: .travis.yml:29-34); this is the
    analog for the TCP/req_id wire + C++ engine path (core/server.cc),
    measuring aggregate push+pull goodput for a 64MB tensor split into
    4MB partitions.  vs_baseline is self-calibrating: the fraction of this
    host's raw Python loopback echo floor (same socket API, no protocol,
    no summing, no store) that the full PS semantics sustain — the honest
    "how much does the KV layer cost over the transport" number.
    """
    import socket
    import subprocess
    import sys
    import threading

    import numpy as np

    from byteps_tpu.server.client import PSSession

    def echo_floor(nbytes: int, reps: int) -> float:
        """Raw synchronous send+recv echo over loopback — the transport
        ceiling for a Python client on this host."""
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        eport = srv.getsockname()[1]

        def serve():
            c, _ = srv.accept()
            buf = bytearray(nbytes)
            view = memoryview(buf)
            for _ in range(reps + 1):
                got = 0
                while got < nbytes:
                    r = c.recv_into(view[got:], nbytes - got)
                    if r == 0:
                        return
                    got += r
                c.sendall(buf)

        th = threading.Thread(target=serve, daemon=True)
        th.start()
        c = socket.create_connection(("127.0.0.1", eport))
        data = bytes(nbytes)
        out = bytearray(nbytes)
        oview = memoryview(out)

        def rt():
            c.sendall(data)
            got = 0
            while got < nbytes:
                got += c.recv_into(oview[got:], nbytes - got)

        rt()  # warm
        t0 = time.perf_counter()
        for _ in range(reps):
            rt()
        dt = time.perf_counter() - t0
        c.close()
        srv.close()
        return 2 * nbytes * reps / dt / 1e9

    from byteps_tpu.utils.hermetic import cpu_subprocess_env


    # BENCH_PS_COMPRESSOR: measure EFFECTIVE goodput with a compressed
    # wire — logical gradient bytes synced per second while the TCP link
    # carries the compressed stream (the reference's slow-network pitch:
    # compression buys wire bytes, docs/performance.md:5-26).  Accepts a
    # shorthand name or full "k=v,k=v" kwargs.
    comp_env = os.environ.get("BENCH_PS_COMPRESSOR", "")
    comp_presets = {
        "onebit": {"compressor": "onebit"},
        "dithering": {"compressor": "dithering", "k": "15", "seed": "5",
                      "partition": "linear", "normalize": "max"},
        "dithering_elias": {"compressor": "dithering", "k": "15",
                            "seed": "5", "partition": "linear",
                            "normalize": "max", "coding": "elias"},
    }
    comp_kw = None
    if comp_env:
        comp_kw = comp_presets.get(comp_env) or dict(
            kv.split("=", 1) for kv in comp_env.split(","))

    # Engines beyond the core count only add context switches to the
    # serve path (measured -10% goodput at 4 engines on a 1-core host).
    proc, port = _boot_ps_server(
        engine_threads=min(4, os.cpu_count() or 4))
    try:
        sess = PSSession(["127.0.0.1"], [port], worker_id=0, num_servers=1,
                         wire_conns=int(os.environ.get(
                             "BYTEPS_TPU_WIRE_CONNS", "2")),
                         compress_threads=int(os.environ.get(
                             "BYTEPS_TPU_COMPRESS_THREADS", "2")),
                         **({"min_compress_bytes": 0} if comp_kw else {}))
        x = np.random.default_rng(0).standard_normal(
            16 << 20, dtype=np.float32)            # 64 MB
        wire_detail = {}
        if comp_kw:
            from byteps_tpu.server import wire as _wire
            sess.register_compressor(1, comp_kw)
            # Size one 4MB PARTITION (what the session actually ships, with
            # its own per-partition norm) — encoding the whole 64MB in one
            # call would also spike the elias emitter's per-bit temporaries.
            part = x[:1 << 20]
            blob = _wire.WireCompressor(dict(comp_kw)).encode(0, part)
            wire_detail = {
                "compressor": ",".join(f"{k}={v}"
                                       for k, v in sorted(comp_kw.items())),
                "wire_bytes_per_partition": len(blob),
                "wire_reduction": round(part.nbytes / len(blob), 2),
            }
            if comp_kw.get("coding") == "elias":
                # The bench tensor is dense standard-normal — the regime
                # where elias roughly ties the dense packing.  Also report
                # the heavy-tailed (sparse-quantizing) regime elias is FOR
                # (real gradients: most levels quantize to 0).
                sp = (part * (np.random.default_rng(1)
                              .random(part.size) < 0.1)).astype(np.float32)
                sblob = _wire.WireCompressor(dict(comp_kw)).encode(0, sp)
                wire_detail["wire_reduction_sparse_gradient"] = round(
                    sp.nbytes / len(sblob), 2)
        sess.push_pull(1, x)                       # init push + warm path
        reps = int(os.environ.get("BENCH_PS_REPS", "10"))
        t0 = time.perf_counter()
        for _ in range(reps):
            sess.push_pull(1, x)
        dt = time.perf_counter() - t0
        sess.close()
        goodput = 2 * x.nbytes * reps / dt / 1e9   # logical push+pull bytes
        floor = echo_floor(x.nbytes, reps)
        print(json.dumps({
            "metric": ("ps_wire_goodput_compressed" if comp_kw
                       else "ps_wire_goodput"),
            "value": round(goodput, 3),
            "unit": "GB/s",
            "vs_baseline": round(goodput / floor, 3),
            "detail": {
                "tensor_mbytes": round(x.nbytes / 1e6, 1),
                "reps": reps,
                "partitions": -(-x.nbytes // (4 << 20)),
                "transport": "loopback TCP, req_id-multiplexed",
                "raw_loopback_echo_floor_gbps": round(floor, 3),
                **wire_detail,
                "note": "vs_baseline = fraction of this host's raw Python "
                        "loopback echo floor sustained by full PS "
                        "semantics (partitioned, summed, round-tracked)"
                        + ("; goodput counts LOGICAL f32 bytes — the wire "
                           "carries the compressed stream" if comp_kw
                           else ""),
                **_device_stamp(),
            },
        }))
    finally:
        proc.kill()
        proc.wait()


def _probe_backend_subprocess(deadline: float) -> str:
    """Poll backend availability in SHORT-LIVED subprocesses until deadline.

    A wedged device tunnel makes jax.devices() block forever, and a
    transiently-held chip (another process finishing up) makes it raise —
    both must not cost this process its ability to report.  Each probe is a
    fresh interpreter killed at a short per-attempt timeout: a block is
    contained (killed child, no parent state), a raise is retried until the
    chip frees up.  Returns "" on success or the last error string.
    """
    import subprocess
    import sys

    last_err = "no probe attempted"
    while time.time() < deadline:
        per_try = min(90.0, max(15.0, deadline - time.time()))
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(len(jax.devices()))"],
                capture_output=True, text=True, timeout=per_try)
        except subprocess.TimeoutExpired:
            last_err = (f"backend init probe blocked >{per_try:.0f}s "
                        f"(device tunnel wedged?)")
            continue
        if proc.returncode == 0:
            return ""
        last_err = proc.stderr.strip()[-500:] or f"probe rc={proc.returncode}"
        time.sleep(2.0)
    return last_err


def _error_record(err: str) -> None:
    print(json.dumps({
        "metric": "bench_backend_init",
        "value": 0.0,
        "unit": "error",
        "vs_baseline": 0.0,
        "detail": {"error": err, **_device_stamp()},
    }), flush=True)


def _init_inprocess(timeout_s: float) -> str:
    """Watchdog the actual in-process backend init (daemon-thread deadline).

    The subprocess pre-probe seeing a free chip does not guarantee THIS
    process's init succeeds (another process can grab the chip in between,
    or the tunnel can wedge).  Returns "" on success or an error string —
    the caller decides whether to fall back.
    """
    import threading

    done = threading.Event()
    info = {}

    def probe():
        try:
            import jax
            info["devices"] = len(jax.devices())
        except Exception as e:  # backend init failure is also a result
            info["error"] = repr(e)
        done.set()

    threading.Thread(target=probe, daemon=True).start()
    if not done.wait(timeout_s):
        return (f"JAX backend init did not complete within {timeout_s:.0f}s "
                f"despite a healthy pre-probe")
    return info.get("error", "")


def _init_backend_or_fallback(timeout_s: float) -> None:
    """Make sure a backend comes up — or re-exec a hermetic CPU fallback.

    Round-3 postmortem: BENCH_r03 recorded only an error because the one
    in-process probe hit a busy/wedged tunnel.  Now: (1) retry cheap
    subprocess probes until the deadline so a transiently-held chip is
    ridden out; (2) if the device never appears (or is snatched between
    probe and init), re-run this bench in a hermetic CPU child (small
    model) so the driver still records a real measurement, honestly
    labelled — the bench must produce a number regardless of tunnel state.
    """
    if os.environ.get("BENCH_CPU_FALLBACK_CHILD", "0") == "1":
        # We ARE the fallback child.  The env pins JAX_PLATFORMS=cpu, but
        # site platform plugins can override the env var — pin the config
        # knob too (same recipe as the dryrun child in __graft_entry__).
        import jax
        jax.config.update("jax_platforms", "cpu")
        return
    if os.environ.get("BENCH_FORCE_CPU", "0") == "1":
        return  # main() already pinned this process to CPU; no device probe
    err = _probe_backend_subprocess(time.time() + timeout_s)
    if not err:
        err = _init_inprocess(120.0)
        if not err:
            return
    _cpu_last_resort(f"device backend unavailable ({err})")


def _cpu_fallback_env(reason: str) -> dict:
    """Hermetic CPU child env: ONE virtual device, matching the real
    bench's single-chip shape (8 devices time-slicing one core would turn
    the efficiency ratio into an oversubscription artifact) — and the
    small model forced (a BENCH_MODEL the driver set for TPU would be
    infeasible on CPU).  Machinery mode keeps 8 devices — its metric
    compares collective strategies over a real mesh axis.  `reason` must
    say WHY the fallback ran (tunnel outage vs device-side bench failure
    — the note is the record's provenance label)."""
    from byteps_tpu.utils.hermetic import (cpu_subprocess_env,
                                           force_host_device_count)

    machinery = os.environ.get("BENCH_MACHINERY", "0") == "1"
    env = cpu_subprocess_env({
        "BENCH_CPU_FALLBACK_CHILD": "1",
        "BENCH_NOTE": f"cpu-fallback: {reason}",
    })
    env.pop("BENCH_MODEL", None)
    if not machinery:
        env["BENCH_SMALL"] = "1"
    force_host_device_count(env, 8 if machinery else 1)
    return env


def _run_bench_child(env: dict, timeout: float) -> tuple:
    """Run this bench script in a subprocess; (rc, captured stdout).

    Stdout is captured so the PARENT controls what the driver sees —
    exactly one JSON line per run even when a child half-emits before
    dying.  The child's stderr tail is forwarded to our stderr for
    debuggability.  A timeout kills the child (rc=124)."""
    import subprocess
    import sys

    try:
        proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                              env=env, timeout=timeout,
                              capture_output=True, text=True)
        rc, out, errtxt = proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        def _s(b):
            return (b.decode(errors="replace")
                    if isinstance(b, bytes) else (b or ""))
        rc, out, errtxt = 124, _s(e.stdout), _s(e.stderr)
    if errtxt:
        sys.stderr.write(errtxt[-3000:])
        sys.stderr.flush()
    return rc, out


def _emit_child_result(rc: int, out: str, extra_detail: dict = None) -> None:
    """Print the child's JSON line and exit 0 on success; return otherwise
    so the caller can try the next recovery step.  `extra_detail` keys are
    merged into the record's detail when the line parses (best-effort —
    an unparseable line still ships verbatim: one-JSON-line contract)."""
    if rc == 0 and out.strip():
        line = out.strip().splitlines()[-1]
        if extra_detail:
            try:
                rec = json.loads(line)
                if (isinstance(rec, dict)
                        and isinstance(rec.setdefault("detail", {}), dict)):
                    rec["detail"].update(extra_detail)
                    line = json.dumps(rec)
            except (ValueError, TypeError, AttributeError):
                pass
        print(line, flush=True)
        os._exit(0)


def main():
    if os.environ.get("BENCH_FORCE_CPU", "0") == "1":
        from byteps_tpu.utils.hermetic import force_host_device_count
        if ("xla_force_host_platform_device_count"
                not in os.environ.get("XLA_FLAGS", "")):
            force_host_device_count(os.environ, 8)  # keep a user-set count
        import jax
        jax.config.update("jax_platforms", "cpu")
    if os.environ.get("BENCH_MACHINERY", "0") == "1":
        _init_backend_or_fallback(float(os.environ.get("BENCH_INIT_TIMEOUT",
                                                       "480")))
        bench_machinery()
    elif os.environ.get("BENCH_PS", "0") == "1":
        bench_ps()           # host-only: no device backend involved
    elif os.environ.get("BENCH_WIRE", "0") == "1":
        bench_wire()         # host-only: no device backend involved
    elif os.environ.get("BENCH_FUSION", "0") == "1":
        bench_fusion()       # host-only: no device backend involved
    elif os.environ.get("BENCH_FAULT", "0") == "1":
        bench_fault()        # host-only: no device backend involved
    elif os.environ.get("BENCH_ELASTIC", "0") == "1":
        bench_elastic()      # host-only: no device backend involved
    elif os.environ.get("BENCH_TELEMETRY", "0") == "1":
        bench_telemetry()    # host-only: no device backend involved
    elif os.environ.get("BENCH_TRACE", "0") == "1":
        bench_trace()        # host-only: no device backend involved
    elif os.environ.get("BENCH_AUDIT", "0") == "1":
        bench_audit()        # host-only: no device backend involved
    elif os.environ.get("BENCH_DOCTOR", "0") == "1":
        bench_doctor()       # host-only: no device backend involved
    elif os.environ.get("BENCH_FLEET", "0") == "1":
        bench_fleet()        # host-only: no device backend involved
    elif os.environ.get("BENCH_SERVEROPT", "0") == "1":
        bench_serveropt()    # host-only: no device backend involved
    elif os.environ.get("BENCH_HIER", "0") == "1":
        bench_hier()         # host-only: no device backend involved
    elif os.environ.get("BENCH_AUTOTUNE", "0") == "1":
        bench_autotune()     # host-only: no device backend involved
    elif os.environ.get("BENCH_KNOB", "0") == "1":
        bench_knob()         # host-only: no device backend involved
    elif os.environ.get("BENCH_SPARSE", "0") == "1":
        bench_sparse()       # host-only: no device backend involved
    elif os.environ.get("BENCH_CNN", ""):
        # Validate the name BEFORE the (possibly minutes-long) backend
        # probe so a typo still honors the one-JSON-line contract.
        from byteps_tpu.models.cnn import CNN_NAMES
        if os.environ["BENCH_CNN"] not in CNN_NAMES:
            _error_record(f"unknown BENCH_CNN={os.environ['BENCH_CNN']!r}; "
                          f"options: {sorted(CNN_NAMES)}")
            raise SystemExit(3)
        _init_backend_or_fallback(float(os.environ.get("BENCH_INIT_TIMEOUT",
                                                       "480")))
        try:
            bench_cnn()
        except Exception as e:  # noqa: BLE001 — one-JSON-line contract
            # Device-side failure AFTER backend init (OOM, tunnel drop
            # mid-step): same guarantee as the flagship ladder — fall back
            # to an honestly-labelled hermetic CPU run rather than dying
            # with a traceback and no record.  The fallback child itself
            # must propagate failures (the parent emits the error record).
            if (os.environ.get("BENCH_CPU_FALLBACK_CHILD", "0") == "1"
                    or os.environ.get("BENCH_FORCE_CPU", "0") == "1"):
                raise
            _cpu_last_resort(f"device cnn bench failed: {e!r:.300}")
    elif (os.environ.get("BENCH_EXEC_CHILD", "0") == "1"
          or os.environ.get("BENCH_FORCE_CPU", "0") == "1"):
        # Execution child (or explicit local CPU mode): actually run the
        # bench; failures propagate as a nonzero rc for the parent.
        if os.environ.get("BENCH_CPU_FALLBACK_CHILD", "0") == "1":
            import jax
            jax.config.update("jax_platforms", "cpu")
        bench_flagship()
    else:
        _flagship_orchestrate()


def _latest_onchip_archive(runs_dir: str = None) -> dict:
    """Most recent archived on-chip flagship record (bench_runs/*.jsonl),
    trimmed to the fields a reader needs to connect a CPU-fallback record
    to real-TPU evidence.  Empty dict when no archive exists.

    The scan covers SWEEP archives too, not just *onchip* files: a
    record qualifies via its detail.mfu > 0, which only a real
    accelerator produces (peak_bf16_flops is 0 off-TPU), so a
    mid-wedge round whose only on-chip evidence is a sweep entry still
    surfaces it."""
    import glob

    try:
        if runs_dir is None:
            runs_dir = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "bench_runs")
        # Per-file mtime guard: a file vanishing between glob and sort
        # must skip THAT file, not abort the whole scan into the blanket
        # except below (advisor r4).  Curated *onchip* archives outrank
        # sweep files (a sweep's last mfu>0 line is whatever geometry
        # ran last, not the flagship anchor a reader wants first).
        stamped = []
        for p in glob.glob(os.path.join(runs_dir, "*.jsonl")):
            try:
                stamped.append(("onchip" in os.path.basename(p),
                                os.path.getmtime(p), p))
            except OSError:
                continue
        files = [p for _, _, p in sorted(stamped)]
        for path in reversed(files):
            with open(path) as f:
                lines = [ln for ln in f.read().splitlines() if ln.strip()]
            for ln in reversed(lines):
                # One truncated/malformed line (a child killed mid-write —
                # the very scenario this lookup serves) must not abort the
                # scan: skip it and keep looking.
                try:
                    rec = json.loads(ln)
                    res = rec.get("result", rec)
                    det = res.get("detail", {})
                    ok = (det.get("mfu") or 0) > 0
                except (ValueError, TypeError, AttributeError):
                    continue
                if ok:
                    import datetime

                    # Prefer a timestamp recorded IN the line (a fresh
                    # clone's file mtime is checkout time, not
                    # measurement time — advisor r4); fall back to mtime.
                    stamp = rec.get("archived_at") or rec.get("ts")
                    if not stamp:
                        try:
                            stamp = datetime.datetime.fromtimestamp(
                                os.path.getmtime(path)
                            ).strftime("%Y-%m-%d %H:%M")
                        except OSError:
                            stamp = "unknown"
                    return {
                        "source": os.path.basename(path),
                        "archived_at": stamp,
                        "metric": res.get("metric"),
                        "value": res.get("value"),
                        "vs_baseline": res.get("vs_baseline"),
                        "tokens_per_sec": det.get(
                            "framework_tokens_per_sec"),
                        "mfu": det.get("mfu"),
                        "batch": det.get("batch"), "seq": det.get("seq"),
                        "attn_impl": det.get("attn_impl"),
                    }
    except Exception:   # archive trouble must never break the fallback
        pass
    return {}


def _cpu_last_resort(reason: str, timeout: float = 1800.0) -> None:
    """Final recovery step: a hermetic CPU child, honestly labelled.  The
    bench must produce a number regardless of tunnel state — this is the
    round-3 postmortem guarantee.  Never returns."""
    env = _cpu_fallback_env(reason)
    env["BENCH_EXEC_CHILD"] = "1"
    rc, out = _run_bench_child(env, timeout=timeout)
    # Keep the record honest (the note says cpu-fallback) but carry the
    # last driver-identical on-chip measurement alongside, so a
    # wedged-tunnel round still points at real-TPU evidence.
    arch = _latest_onchip_archive()
    _emit_child_result(rc, out,
                       extra_detail={"last_onchip_archive": arch}
                       if arch else None)
    _error_record(f"cpu-fallback bench child failed (rc={rc}): "
                  f"{out.strip()[-200:]}")
    os._exit(3)


def _flagship_orchestrate() -> None:
    """Drive the flagship bench from a backend-free parent.

    The parent NEVER initializes a device backend: each attempt runs in a
    disposable child, so a failed attempt releases the chip and the next
    child can grab it (an in-process init would hold the TPU's exclusive
    per-process lock across the retry).  Recovery ladder: device bench ->
    conservative-config device bench (skipped when the first attempt
    TIMED OUT — a wedge would just wedge again) -> hermetic CPU child.
    The whole ladder fits BENCH_TOTAL_BUDGET seconds (default 2200, within
    the previous probe+fallback bound, so an external driver timeout tuned
    to the old behavior still sees the guaranteed JSON line).  Contract
    for the driver: exactly one JSON line; rc=0 iff it is a real
    measurement, rc=3 with an error record otherwise.
    """
    budget = float(os.environ.get("BENCH_TOTAL_BUDGET", "2200"))
    deadline = time.time() + budget
    cpu_reserve = 700.0   # always leave room for the guaranteed CPU rung

    def remaining(reserve: float) -> float:
        return max(60.0, deadline - time.time() - reserve)

    timeout_s = min(float(os.environ.get("BENCH_INIT_TIMEOUT", "480")),
                    remaining(cpu_reserve + 600))
    err = _probe_backend_subprocess(time.time() + timeout_s)
    if err:
        _cpu_last_resort(f"device backend unavailable ({err})",
                         timeout=remaining(0))

    env = dict(os.environ)
    env["BENCH_EXEC_CHILD"] = "1"
    rc, out = _run_bench_child(env, timeout=remaining(cpu_reserve + 400))
    _emit_child_result(rc, out)
    if rc != 124 and not os.environ.get("BENCH_MODEL"):
        # Fast failure (not a wedge): one retry with the conservative
        # config, in case a newer tuned default misbehaves on the real
        # chip.  Exactly the BENCH_r02 driver-verified configuration
        # (dense attention, full-logits CE, full remat, batch 16): the
        # tuned default's batch 64 is only feasible because flash never
        # materializes the S^2 logits, so the fallback must drop batch
        # along with the kernel.  Only meaningful for the default
        # bert_large path — for an explicit BENCH_MODEL these pins would
        # RAISE the memory footprint (dense + full-logits CE at the
        # model's native seq at a higher batch), so those runs go
        # straight to the CPU rung instead.
        env.update({"BENCH_CE_CHUNK": "0", "BENCH_ATTN": "dense",
                    "BENCH_ATTN_BLOCK": "0", "BENCH_BATCH": "16",
                    "BENCH_REMAT": "1", "BENCH_REMAT_POLICY": "none",
                    "BENCH_NOTE": ("conservative-retry: default config "
                                   f"failed in child (rc={rc})")})
        rc, out = _run_bench_child(env, timeout=remaining(cpu_reserve))
        _emit_child_result(rc, out)
    # Device attempts exhausted (wedged after a healthy probe, or both
    # configs failed): still record a real number.
    _cpu_last_resort(f"device bench attempts failed (last rc={rc})",
                     timeout=remaining(0))


if __name__ == "__main__":
    main()
