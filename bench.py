"""Benchmark: flagship (BERT-large-class) DP training throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference's headline number is ~90% scaling efficiency for BERT-large
DP training (reference: README.md:38-46, BASELINE.md).  Scaling efficiency
is throughput-with-the-framework / ideal-throughput; on a single chip the
ideal is the raw jitted train step with no distribution framework, so
`efficiency = framework_step_throughput / raw_step_throughput` measured on
the same hardware — the framework's communication/scheduling overhead is
exactly what scaling efficiency penalises at scale.  vs_baseline =
efficiency / 0.90 (the reference's 256-GPU result; >1.0 beats it).

Runs on whatever jax.devices() offers: the real TPU chip under the driver,
or the 8-device virtual CPU mesh locally (BENCH_SMALL=1 shrinks the model
for quick local runs).
"""

from __future__ import annotations

import json
import os
import time


def main():
    if os.environ.get("BENCH_FORCE_CPU", "0") == "1":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp
    import optax

    import byteps_tpu as bps
    from byteps_tpu.models import transformer as tfm

    on_tpu = jax.devices()[0].platform == "tpu"
    small = os.environ.get("BENCH_SMALL", "0") == "1" or not on_tpu
    if small:
        cfg = tfm.get_config("tiny", causal=True)
        batch, seq, steps = 8 * max(1, jax.device_count()), 128, 5
    else:
        # Full BERT-large geometry (reference benchmark: README.md:38-46),
        # causal-LM objective, bf16 activations, per-layer remat.
        cfg = tfm.get_config("bert_large", causal=True, vocab_size=32768,
                             max_seq_len=512)
        batch, seq, steps = 16 * jax.device_count(), 512, 10

    mesh = bps.make_mesh()  # all devices on dp
    params = tfm.init_params(jax.random.key(0), cfg)
    toks, tgts = tfm.synthetic_batch(jax.random.key(1), batch, seq, cfg)

    def loss_fn(p, b):
        return tfm.loss_fn(p, b, cfg)

    def time_steps(step, params, opt_state, n):
        params, opt_state, loss = step(params, opt_state, (toks, tgts))
        float(loss)  # warmup + compile
        t0 = time.perf_counter()
        for _ in range(n):
            params, opt_state, loss = step(params, opt_state, (toks, tgts))
            float(loss)  # per-step sync: async runtimes may otherwise report
            # dispatch rate, not execution rate
        return n * batch * seq / (time.perf_counter() - t0)

    # Framework path: DistributedOptimizer (bucketed priority all-reduce).
    opt = bps.DistributedOptimizer(optax.adamw(1e-4))
    step = bps.build_train_step(loss_fn, opt, mesh, donate=False)
    fw_tps = time_steps(step, params, opt.init(params), steps)

    # Ideal path: same model/optimizer, no distribution framework, one shard
    # of the global batch on one device -> ideal per-chip throughput.
    raw_opt = optax.adamw(1e-4)
    n_dev = jax.device_count()
    rb = max(1, batch // n_dev)
    rtoks, rtgts = toks[:rb], tgts[:rb]

    def raw_step(p, s, b):
        loss, g = jax.value_and_grad(loss_fn)(p, b)
        u, s = raw_opt.update(g, s, p)
        return optax.apply_updates(p, u), s, loss

    rstep = jax.jit(raw_step)
    p, s, l = rstep(params, raw_opt.init(params), (rtoks, rtgts))
    float(l)
    t0 = time.perf_counter()
    for _ in range(steps):
        p, s, l = rstep(p, s, (rtoks, rtgts))
        float(l)
    raw_tps = steps * rb * seq / (time.perf_counter() - t0)

    efficiency = fw_tps / (raw_tps * n_dev)
    print(json.dumps({
        "metric": "bert_large_dp_scaling_efficiency" if not small
        else "tiny_dp_scaling_efficiency",
        "value": round(efficiency, 4),
        "unit": "fraction_of_ideal",
        "vs_baseline": round(efficiency / 0.90, 4),
        "detail": {
            "framework_tokens_per_sec": round(fw_tps),
            "ideal_tokens_per_sec_per_chip": round(raw_tps),
            "devices": n_dev,
            "batch": batch, "seq": seq,
            "model": "bert_large" if not small else "tiny",
        },
    }))


if __name__ == "__main__":
    main()
